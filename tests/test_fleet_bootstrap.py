"""Snapshot-shipping bootstrap contracts (docs/SERVING.md §Adding a
replica under live traffic).

The load-bearing claims:

1. **Export**: any mutable replica serves its committed generation over
   ``GET /admin/snapshot`` — a digest-stamped manifest plus ranged
   chunks, with a generation precondition so a compaction mid-transfer
   is a typed 409 (restart), never a file stitched from two generations.
2. **Install is atomic**: every failure leg — torn chunk, digest
   mismatch, the ``fleet.snapshot_ship`` fault point standing in for a
   full disk — leaves the prior state serving and no staged debris.
3. **In-process re-seed**: ``POST /admin/bootstrap`` on a divergent
   follower abandons its lineage (epochs cleared BEFORE the pointer
   commit — no abandoned record may replay onto the new base) and the
   primary's parked shipper resumes on its re-probe with no primary
   restart.
4. **Retention floor**: a primary compaction never prunes WAL epochs a
   live follower's cursor still needs — a merely-lagging follower keeps
   catching up from the WAL instead of being force-parked behind the
   fold.

The under-live-load versions of these legs (blank-follower join,
rolling restart, partition/rejoin) run in ``scripts/fleet_soak.py``.
"""

import hashlib
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.fleet import bootstrap
from knn_tpu.fleet.bootstrap import SnapshotInstallError
from knn_tpu.fleet.replica import FleetReplica
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.mutable.compact import Compactor
from knn_tpu.mutable.engine import MutableEngine
from knn_tpu.resilience import faults
from knn_tpu.resilience.errors import DataError
from knn_tpu.serve import artifact
from knn_tpu.serve.artifact import save_index
from knn_tpu.serve.server import ServeApp, make_server


def _problem(rng, n=80, d=4, c=3):
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)
    train_y = rng.integers(0, c, n).astype(np.int32)
    return Dataset(train_x, train_y)


def _artifact(model, tmp_path, name):
    out = tmp_path / name
    if not (out / "manifest.json").exists():
        save_index(model, out)
    return out


def _http(base, path, payload=None, method=None, timeout=30):
    req = urllib.request.Request(
        base + path,
        data=(json.dumps(payload).encode() if payload is not None
              else None),
        headers=({"Content-Type": "application/json"} if payload
                 else {}),
        method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _http_raw(base, path, timeout=30):
    req = urllib.request.Request(base + path)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


class _Replica:
    """One in-process serve replica (no warmup — tests flip ready)."""

    def __init__(self, model, index_dir, **kw):
        self.app = ServeApp(model, max_batch=8, max_wait_ms=0.2,
                            index_path=str(index_dir), **kw)
        self.server = make_server(self.app)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        self.app.ready = True
        host, port = self.server.server_address[:2]
        self.url = f"http://{host}:{port}"

    def close(self):
        self.server.shutdown()
        self.server.server_close()
        self.app.close()


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


@pytest.fixture
def rng():
    return np.random.default_rng(7)


# -- 1. snapshot export ------------------------------------------------------


class TestSnapshotExport:
    def test_manifest_digests_match_disk(self, rng, tmp_path):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        root = _artifact(model, tmp_path, "idx")
        man = bootstrap.snapshot_manifest(root)
        assert [f["name"] for f in man["files"]] == [
            artifact.MANIFEST_NAME, artifact.ARRAYS_NAME]
        assert man["generation"] == 0 and man["wal_cursor"] == 0
        for entry in man["files"]:
            data = (root / entry["name"]).read_bytes()
            assert entry["size"] == len(data)
            assert entry["sha256"] == hashlib.sha256(data).hexdigest()

    def test_chunk_generation_precondition_is_typed(self, rng, tmp_path):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        root = _artifact(model, tmp_path, "idx")
        with pytest.raises(DataError, match="superseded"):
            bootstrap.read_chunk(root, artifact.ARRAYS_NAME, 0, 64,
                                 generation=5)

    def test_chunk_refuses_non_snapshot_files(self, rng, tmp_path):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        root = _artifact(model, tmp_path, "idx")
        for name in ("CURRENT.json", "../secrets", "epochs/epoch-1.jsonl"):
            with pytest.raises(DataError, match="not a snapshot file"):
                bootstrap.read_chunk(root, name, 0, 64, generation=0)

    def test_http_chunks_reassemble_bit_exact(self, rng, tmp_path):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        root = _artifact(model, tmp_path, "idx")
        rep = _Replica(model, root, mutable=True)
        try:
            st, man = _http(rep.url, "/admin/snapshot")
            assert st == 200
            entry = next(f for f in man["files"]
                         if f["name"] == artifact.ARRAYS_NAME)
            got = b""
            while len(got) < entry["size"]:
                st, chunk = _http_raw(
                    rep.url,
                    f"/admin/snapshot?file={entry['name']}"
                    f"&offset={len(got)}&length=1024"
                    f"&generation={man['generation']}")
                assert st == 200 and chunk
                got += chunk
            assert got == (root / entry["name"]).read_bytes()
            # Stale generation precondition: typed 409, not bytes.
            st, doc = _http(rep.url,
                            f"/admin/snapshot?file={entry['name']}"
                            f"&offset=0&length=64&generation=9")
            assert st == 409 and "superseded" in doc["error"]
        finally:
            rep.close()


# -- 2. boot-time install (blank directory) ---------------------------------


class TestInstallSnapshot:
    def test_blank_dir_install_is_bootable(self, rng, tmp_path):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        src_root = _artifact(model, tmp_path, "src")
        rep = _Replica(model, src_root, mutable=True)
        blank = tmp_path / "blank"
        try:
            assert not bootstrap.artifact_present(blank)
            doc = bootstrap.install_snapshot(blank, rep.url)
            assert doc["folded_seq"] == 0 and doc["bytes"] > 0
            assert bootstrap.artifact_present(blank)
            base_dir, current = artifact.resolve_mutable_base(blank)
            assert current["base"].startswith("generations/")
            loaded = artifact.load_index(base_dir)
            eng = MutableEngine(loaded, blank, delta_cap=64,
                                current=current, base_dir=base_dir)
            try:
                assert eng.seq == 0  # the WAL cursor the shipper resumes at
            finally:
                eng.close()
        finally:
            rep.close()

    def test_fault_point_leaves_blank_dir_blank(self, rng, tmp_path):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        rep = _Replica(model, _artifact(model, tmp_path, "src"),
                       mutable=True)
        blank = tmp_path / "blank2"
        try:
            with faults.inject("fleet.snapshot_ship=once") as plan:
                with pytest.raises(OSError, match="injected"):
                    bootstrap.install_snapshot(blank, rep.url)
            assert plan.stats()["fleet.snapshot_ship"]["fired"] == 1
            assert not bootstrap.artifact_present(blank)
            assert not list(blank.glob(".bootstrap-*"))  # staging removed
        finally:
            rep.close()

    def test_torn_chunk_and_digest_mismatch_are_typed(self, rng, tmp_path,
                                                      monkeypatch):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        rep = _Replica(model, _artifact(model, tmp_path, "src"),
                       mutable=True)
        blank = tmp_path / "blank3"
        real = bootstrap.forward_bytes
        try:
            def torn(method, url, body, timeout):
                status, data = real(method, url, body, timeout=timeout)
                return status, data[:-1]  # one byte short of the range

            monkeypatch.setattr(bootstrap, "forward_bytes", torn)
            with pytest.raises(SnapshotInstallError, match="torn chunk"):
                bootstrap.download_snapshot(rep.url, blank)
            assert not list(blank.glob(".bootstrap-*"))

            def corrupt(method, url, body, timeout):
                status, data = real(method, url, body, timeout=timeout)
                return status, bytes(len(data))  # right size, wrong bytes

            monkeypatch.setattr(bootstrap, "forward_bytes", corrupt)
            with pytest.raises(SnapshotInstallError,
                               match="digest mismatch"):
                bootstrap.download_snapshot(rep.url, blank)
            assert not list(blank.glob(".bootstrap-*"))
        finally:
            rep.close()


# -- 3. in-process re-seed + parked-shipper resume ---------------------------


class TestInProcessBootstrap:
    def test_primary_refuses_to_bootstrap_itself(self, rng, tmp_path):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        rep = _Replica(model, _artifact(model, tmp_path, "p"),
                       mutable=True,
                       replicate_to=["http://127.0.0.1:9"],
                       replicate_ack="none")
        try:
            st, doc = _http(rep.url, "/admin/bootstrap",
                            {"from": "http://127.0.0.1:9"})
            assert st == 409 and "SOURCE" in doc["error"]
        finally:
            rep.close()

    def test_install_failure_leaves_prior_state_serving(self, rng,
                                                        tmp_path, obs_on):
        """The ISSUE's mid-transfer failure leg: the ``fleet.snapshot_ship``
        fault fires between verify and commit — the 502 carries
        ``prior_state_serving`` and the target's own lineage (model,
        version, WAL) is untouched."""
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        src = _Replica(model, _artifact(model, tmp_path, "src"),
                       mutable=True)
        tgt = _Replica(model, _artifact(model, tmp_path, "tgt"),
                       mutable=True)
        try:
            tgt.app.mutable.apply_insert(
                np.ones((1, 4), np.float32), [0], 0)
            before_seq = tgt.app.mutable.seq
            before_version = tgt.app.index_version
            with faults.inject("fleet.snapshot_ship=once"):
                st, doc = _http(tgt.url, "/admin/bootstrap",
                                {"from": src.url})
            assert st == 502 and doc["prior_state_serving"] is True
            assert tgt.app.mutable.seq == before_seq
            assert tgt.app.index_version == before_version
            st, doc = _http(tgt.url, "/predict",
                            {"instances": [[1.0, 0.0, 1.0, 2.0]]})
            assert st == 200 and len(doc["predictions"]) == 1
            # The abandoned staging dir is gone; the lineage's WAL is not.
            assert not list(tgt.app.mutable.root.glob(".bootstrap-*"))
            assert artifact.list_epochs(tgt.app.mutable.root)
        finally:
            src.close()
            tgt.close()

    def test_diverged_follower_recovers_and_shipper_resumes(
            self, rng, tmp_path, obs_on, monkeypatch):
        """The tentpole end to end, in process: a follower with a
        divergent record at the same seq parks the primary's shipper as
        ``diverged`` (typed — never a divergent answer shipped onward);
        ``POST /admin/bootstrap`` re-seeds it from the primary's
        snapshot; the parked shipper's re-probe then resyncs and resumes
        WITHOUT a primary restart."""
        from knn_tpu.fleet import replica as replica_mod

        monkeypatch.setattr(replica_mod, "TERMINAL_RETRY_S", 0.2)
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        follower = _Replica(model, _artifact(model, tmp_path, "f"),
                            mutable=True,
                            follower_of="http://127.0.0.1:9")
        primary = _Replica(model, _artifact(model, tmp_path, "p"),
                           mutable=True, replicate_to=[follower.url],
                           replicate_ack="none")
        try:
            # Divergence: the follower holds seq 1 with DIFFERENT content
            # than the primary's seq 1 (a partitioned ex-primary's
            # unreplicated tail, in miniature).
            follower.app.mutable.apply_insert(
                np.full((1, 4), 9.0, np.float32), [2], 0)
            st, doc = _http(primary.url, "/insert",
                            {"rows": [[1.0, 1.0, 1.0, 1.0]],
                             "labels": [0]})
            assert st == 200

            def shipper_state():
                return primary.app.fleet.export()["followers"][
                    follower.url]["state"]

            deadline = time.monotonic() + 10
            while (shipper_state() != "diverged"
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert shipper_state() == "diverged"

            # Out-of-band re-seed (what the router's auto path drives).
            st, doc = _http(follower.url, "/admin/bootstrap",
                            {"from": primary.url})
            assert st == 200 and doc["bootstrapped"] is True
            # The abandoned lineage's RECORDS are gone (the reseed opens
            # a fresh empty epoch) — its divergent record can never
            # replay onto the new base.
            for _n, path in artifact.list_epochs(
                    follower.app.mutable.root):
                records, _torn = artifact.read_epoch_records(
                    path, tolerate_torn=True)
                assert records == []

            # The parked shipper re-probes (0.2s here) and resumes: the
            # primary's seq-1 record applies cleanly on the re-seeded
            # follower. No primary restart happened.
            deadline = time.monotonic() + 10
            while (shipper_state() != "ok"
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            assert shipper_state() == "ok"
            assert follower.app.mutable.seq == primary.app.mutable.seq
            # The shipper-state gauge is exported for the follower.
            gauges = {i.name for i in obs.registry().instruments()}
            assert "knn_fleet_shipper_state" in gauges
        finally:
            primary.close()
            follower.close()


# -- 4. router-driven re-seed ------------------------------------------------


class TestRouterBootstrap:
    def _diverged_pair(self, rng, tmp_path):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        follower = _Replica(model, _artifact(model, tmp_path, "f"),
                            mutable=True,
                            follower_of="http://127.0.0.1:9")
        primary = _Replica(model, _artifact(model, tmp_path, "p"),
                           mutable=True, replicate_to=[follower.url],
                           replicate_ack="none")
        # Same seq, different content: the divergence drill in miniature.
        follower.app.mutable.apply_insert(
            np.full((1, 4), 9.0, np.float32), [2], 0)
        primary.app.mutable.apply_insert(
            np.ones((1, 4), np.float32), [0], 0)
        return primary, follower

    def _wait(self, cond, timeout=10.0):
        deadline = time.monotonic() + timeout
        while not cond() and time.monotonic() < deadline:
            time.sleep(0.02)
        assert cond()

    def test_manual_bootstrap_route_reseeds_and_audits(
            self, rng, tmp_path, obs_on, monkeypatch):
        from knn_tpu.fleet import replica as replica_mod
        from knn_tpu.fleet.router import RouterApp

        monkeypatch.setattr(replica_mod, "TERMINAL_RETRY_S", 0.2)
        primary, follower = self._diverged_pair(rng, tmp_path)
        app = RouterApp([primary.url, follower.url],
                        health_interval_s=0.1, event_log=True)
        try:
            def parked():
                f = app.set.state(primary.url).followers or {}
                return (f.get(follower.url) or {}).get(
                    "state") == "diverged"

            self._wait(parked)
            # The shipper state is joined into the router's health doc
            # (and therefore /debug/fleet) via the primary's healthz.
            h = app.health()
            assert h["replicas"][primary.url]["followers"][
                follower.url]["state"] == "diverged"
            result = app.bootstrap()  # no follower named: picks the
            assert result["status"] == 200  # parked one
            assert result["body"]["replica"] == follower.url
            assert [e["event"] for e in app.events.recent()
                    if e["event"].startswith("reseed")] == [
                "reseed-begin", "reseed-complete"]
            self._wait(lambda: primary.app.fleet.export()["followers"][
                follower.url]["state"] == "ok")
            assert (follower.app.mutable.seq
                    == primary.app.mutable.seq)
        finally:
            app.close()
            primary.close()
            follower.close()

    def test_auto_failover_flag_drives_the_reseed(self, rng, tmp_path,
                                                  obs_on, monkeypatch):
        """The self-healing loop end to end: ``--auto-failover`` alone —
        no operator call — notices the parked shipper on a health poll,
        drives the bootstrap, and the fleet converges."""
        from knn_tpu.fleet import replica as replica_mod
        from knn_tpu.fleet.router import RouterApp

        monkeypatch.setattr(replica_mod, "TERMINAL_RETRY_S", 0.2)
        primary, follower = self._diverged_pair(rng, tmp_path)
        app = RouterApp([primary.url, follower.url],
                        health_interval_s=0.1, auto_failover=True,
                        event_log=True)
        try:
            self._wait(lambda: primary.app.fleet.export()["followers"][
                follower.url]["state"] == "ok", timeout=15.0)
            assert (follower.app.mutable.seq
                    == primary.app.mutable.seq)
            done = app.events.find("reseed-complete")
            assert done and done[0]["trigger"] == "auto"
            assert app.reseeds == 1
        finally:
            app.close()
            primary.close()
            follower.close()


# -- 5. WAL retention floor --------------------------------------------------


class TestRetentionFloor:
    def _compactor(self, eng, model, floor):
        def swap(new_model, version, hook):
            hook()
            return version

        return Compactor(eng, swap=swap, warm=lambda m: None,
                         threshold=10_000, interval_s=0,
                         retention_floor=floor)

    def test_lagging_follower_holds_epochs_then_prunes(self, rng,
                                                       tmp_path, obs_on):
        """The silent-retention-hazard fix: a fold with a live follower
        cursor behind it defers epoch pruning (counted + surfaced), so
        ``records_since`` still serves the lagging cursor; once the
        follower catches up, the NEXT compaction's cleanup prunes what
        the floor released."""
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        root = _artifact(model, tmp_path, "idx")
        eng = MutableEngine(model, root, delta_cap=256)
        cursor = {"acked": 0}
        comp = self._compactor(eng, model, lambda: cursor["acked"])
        try:
            for v in range(3):
                eng.apply_insert(np.full((1, 4), float(v), np.float32),
                                 [v % 3], 0)
            out = comp.run_once(force=True)
            assert out["compacted"] and out["folded_seq"] == 3
            assert out["epochs_held"] >= 1 and out["epochs_pruned"] == 0
            assert out["retention_floor"] == 0
            held = [i for i in obs.registry().instruments()
                    if i.name == "knn_fleet_wal_retention_held_total"]
            assert held and held[0].value >= 1
            # The lagging cursor is still servable — gapless from seq 1.
            records, seq = eng.records_since(0)
            assert [r["seq"] for r in records] == [1, 2, 3] and seq == 3
            # Follower catches up; the next fold's cleanup prunes.
            eng.apply_insert(np.full((1, 4), 7.0, np.float32), [1], 0)
            cursor["acked"] = eng.seq
            out = comp.run_once(force=True)
            assert out["compacted"] and out["epochs_pruned"] >= 1
            assert out["epochs_held"] == 0
        finally:
            comp.stop()
            eng.close()

    def test_slow_follower_never_parks_behind_fold(self, rng, tmp_path,
                                                   obs_on):
        """Pin the end-to-end hazard: a shipper whose cursor lags a
        compaction must go right on shipping from the retained epochs —
        'lagging' must never become 'terminally parked' merely because
        the primary compacted."""
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        follower = _Replica(model, _artifact(model, tmp_path, "f"),
                            mutable=True,
                            follower_of="http://127.0.0.1:9")
        root = _artifact(model, tmp_path, "p")
        eng = MutableEngine(model, root, delta_cap=256)
        fleet = FleetReplica(eng, role="primary",
                             replicate_to=[follower.url],
                             ship_interval_s=0.02, ack_mode="none")
        comp = self._compactor(eng, model, fleet.retention_floor)
        try:
            # Park the WIRE, not the protocol: with the follower's
            # listener down, the shipper stays 'unreachable' (live — it
            # holds the floor) while the primary writes and compacts.
            follower.server.shutdown()
            follower.server.server_close()
            for v in range(4):
                eng.apply_insert(np.full((1, 4), float(v), np.float32),
                                 [v % 3], 0)
            out = comp.run_once(force=True)
            assert out["compacted"] and out["epochs_held"] >= 1
            assert out["retention_floor"] == 0
            state = fleet.export()["followers"][follower.url]["state"]
            assert state in ("ok", "unreachable")  # NEVER behind_fold
            # The records a catch-up needs survived the fold.
            records, _ = eng.records_since(0)
            assert [r["seq"] for r in records] == [1, 2, 3, 4]
        finally:
            comp.stop()
            fleet.close()
            eng.close()
            follower.app.close()

    def test_router_audits_the_retention_hold(self, rng, tmp_path,
                                              obs_on):
        """A coordinated compaction whose verdict reports held epochs
        lands an ``epoch-retention-hold`` event in the router's audit
        log — 'why is the primary's disk growing' joins to the follower
        holding the floor."""
        from knn_tpu.fleet.router import RouterApp

        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        p = _Replica(model, _artifact(model, tmp_path, "p"),
                     mutable=True,
                     replicate_to=["http://127.0.0.1:9"],
                     replicate_ack="none")
        app = RouterApp([p.url], health_interval_s=0.1, event_log=True)
        try:
            for v in range(3):
                p.app.mutable.apply_insert(
                    np.full((1, 4), float(v), np.float32), [0], 0)
            result = app.coordinated_compact()
            assert result["status"] == 200
            assert result["body"]["epochs_held"] >= 1
            holds = app.events.find("epoch-retention-hold")
            assert holds and holds[0]["retention_floor"] == 0
        finally:
            app.close()
            p.close()

    def test_parked_shippers_do_not_pin_the_log(self, rng, tmp_path):
        """A diverged/behind-fold shipper recovers via bootstrap, not the
        WAL — the floor excludes it, else one dead follower would hold
        every epoch forever."""
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        root = _artifact(model, tmp_path, "idx")
        eng = MutableEngine(model, root, delta_cap=256)
        fleet = FleetReplica(eng, role="primary",
                             replicate_to=["http://127.0.0.1:9"],
                             ack_mode="none")
        try:
            shipper = fleet._shippers["http://127.0.0.1:9"]
            shipper.state = "diverged"
            assert fleet.retention_floor() is None
            shipper.state = "ok"
            assert fleet.retention_floor() == 0
        finally:
            fleet.close()
            eng.close()
