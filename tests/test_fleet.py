"""Replica-set contract tests (docs/SERVING.md §Running a replica set).

The load-bearing claims, replica-side first:

1. **Replicated apply** rides the exact local-mutation validation path:
   in-order records apply AND land in the follower's own WAL (flushed —
   promote and reboot both depend on it); a seq gap is a typed
   :class:`ReplicationGap` carrying the resync cursor; an already-applied
   seq is an idempotent no-op guarded by a content digest; a divergent
   record is a typed refusal, never silent corruption.
2. **Shipping**: the primary's per-follower cursor drains lag, survives
   the ``fleet.wal_ship`` fault point, and the semi-synchronous ack
   holds a mutation's 200 until a follower confirmed its seq.
3. **Promotion** flips a follower to primary in place and records the
   takeover seq; :func:`truncate_wal` drops the unacknowledged tail past
   it (the ex-primary rejoin primitive).
4. **Routing**: reads retry transport failures on a DIFFERENT replica
   (zero client-visible failures while one replica survives), writes go
   only to the primary, typed 503 is the ONLY total-failure answer, and
   a coordinated reload is all-or-nothing with rollback.

The kill-replicas-under-load end-to-end legs live in
``scripts/fleet_soak.py`` (`make fleet-soak`); these tests pin the
per-component contracts tier-1 fast.
"""

import json
import shutil
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.mutable.engine import MutableEngine, truncate_wal
from knn_tpu.mutable.state import (
    MutationConflict,
    ReplicationGap,
    WALDivergence,
)
from knn_tpu.resilience import faults
from knn_tpu.resilience.errors import DataError
from knn_tpu.serve.artifact import save_index
from knn_tpu.serve.server import ServeApp, make_server


def _problem(rng, n=80, d=4, c=3):
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)
    train_y = rng.integers(0, c, n).astype(np.int32)
    return Dataset(train_x, train_y)


def _artifact(model, tmp_path, name):
    out = tmp_path / name
    if not (out / "manifest.json").exists():
        save_index(model, out)
    return out


def _http(base, path, payload=None, method=None, timeout=10):
    req = urllib.request.Request(
        base + path,
        data=(json.dumps(payload).encode() if payload is not None
              else None),
        headers=({"Content-Type": "application/json"} if payload
                 else {}),
        method=method,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class _Replica:
    """One in-process serve replica (no warmup — tests flip ready)."""

    def __init__(self, model, index_dir, **kw):
        self.app = ServeApp(model, max_batch=8, max_wait_ms=0.2,
                            index_path=str(index_dir), **kw)
        self.server = make_server(self.app)
        threading.Thread(target=self.server.serve_forever,
                         daemon=True).start()
        self.app.ready = True
        host, port = self.server.server_address[:2]
        self.url = f"http://{host}:{port}"

    def kill(self):
        """SIGKILL-equivalent: listener + handlers gone, no drain."""
        self.server.shutdown()
        self.server.server_close()

    def close(self):
        self.kill()
        self.app.close()


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


# -- 1. replicated apply (engine level) -------------------------------------


class TestApplyReplicated:
    def _engine(self, rng, tmp_path, name="idx"):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        return model, MutableEngine(
            model, _artifact(model, tmp_path, name), delta_cap=256)

    def test_in_order_apply_is_durable_in_own_wal(self, rng, tmp_path):
        model, src = self._engine(rng, tmp_path, "src")
        dst_dir = _artifact(model, tmp_path, "dst")
        dst = MutableEngine(model, dst_dir, delta_cap=256)
        try:
            src.apply_insert(np.ones((2, 4), np.float32), [0, 1], 0)
            src.apply_delete([10], 0)
            records, seq = src.records_since(0)
            for rec in records:
                assert dst.apply_replicated(rec)["applied"]
            assert dst.seq == seq == 2
            a, b = src.snapshot(), dst.snapshot()
            assert a.count == b.count and a.tomb_pos == b.tomb_pos
            np.testing.assert_array_equal(a.features[:a.count],
                                          b.features[:b.count])
            np.testing.assert_array_equal(a.stable[:a.count],
                                          b.stable[:b.count])
        finally:
            src.close()
            dst.close()
        # The replica's OWN WAL now replays the same state (what promote
        # and reboot both ride).
        dst2 = MutableEngine(model, dst_dir, delta_cap=256)
        try:
            assert dst2.seq == 2
            assert dst2.snapshot().tomb_pos == frozenset({10})
        finally:
            dst2.close()

    def test_gap_is_typed_with_resync_cursor(self, rng, tmp_path):
        model, src = self._engine(rng, tmp_path, "src")
        dst = MutableEngine(model, _artifact(model, tmp_path, "dst"),
                            delta_cap=256)
        try:
            for v in range(3):
                src.apply_insert(np.full((1, 4), float(v), np.float32),
                                 [0], 0)
            records, _ = src.records_since(0)
            dst.apply_replicated(records[0])
            with pytest.raises(ReplicationGap) as exc:
                dst.apply_replicated(records[2])  # skips seq 2
            assert exc.value.applied_seq == 1
            assert dst.seq == 1  # nothing applied past the refusal
        finally:
            src.close()
            dst.close()

    def test_divergent_record_is_typed_refusal(self, rng, tmp_path):
        """Wrong width / out-of-range label = full local validation:
        never applied, never WAL-appended."""
        model, dst = self._engine(rng, tmp_path, "dst")
        try:
            with pytest.raises(ValueError, match=r"insert rows"):
                dst.apply_replicated({"seq": 1, "op": "insert", "sid0": 80,
                                      "rows": [[1.0, 2.0]],
                                      "values": [0]})
            with pytest.raises(ValueError, match="labels must be in"):
                dst.apply_replicated({"seq": 1, "op": "insert", "sid0": 80,
                                      "rows": [[1.0] * 4],
                                      "values": [99]})
            with pytest.raises(MutationConflict, match="no such row"):
                dst.apply_replicated({"seq": 1, "op": "delete",
                                      "sids": [12345]})
            with pytest.raises(DataError, match="unknown op"):
                dst.apply_replicated({"seq": 1, "op": "merge"})
            assert dst.seq == 0
            records, _ = dst.records_since(0)
            assert records == []  # the WAL is untouched
        finally:
            dst.close()

    def test_truncate_wal_drops_only_the_tail(self, rng, tmp_path):
        model, eng = self._engine(rng, tmp_path, "idx")
        root = _artifact(model, tmp_path, "idx")
        for v in range(4):
            eng.apply_insert(np.full((1, 4), float(v), np.float32),
                             [0], 0)
        eng.close()
        assert truncate_wal(root, cap_seq=2) == 2
        eng2 = MutableEngine(model, root, delta_cap=256)
        try:
            assert eng2.seq == 2
            assert eng2.snapshot().count == 2
        finally:
            eng2.close()

    def test_shipper_cursor_starts_at_the_fold_point(self, rng,
                                                     tmp_path):
        """A primary booted from an ever-compacted artifact (or a
        follower promoted after one) must not ask the WAL for records
        below the fold — the cursor starts AT folded_seq, and only a
        follower that is genuinely behind the fold (gap-409 resync below
        it) reaches the terminal re-seed state."""
        from knn_tpu.fleet.replica import FleetReplica

        model, eng = self._engine(rng, tmp_path, "idx")
        try:
            eng._folded_seq = eng._seq = 7  # as a compacted boot sets
            fleet = FleetReplica(eng, role="primary",
                                 replicate_to=["http://127.0.0.1:9"])
            try:
                shipper = fleet._shippers["http://127.0.0.1:9"]
                assert shipper.acked_seq == 7
                time.sleep(0.15)  # idle ticks: caught-up cursor must
                # never scan below the fold and go terminal
                assert shipper.state == "ok"
            finally:
                fleet.close()
        finally:
            eng.close()

    def test_records_since_behind_fold_is_typed(self, rng, tmp_path):
        """A cursor older than the oldest surviving record means the
        follower must re-seed — typed, never a partial ship. But while
        the records BELOW the fold still exist on disk (the retention
        floor held them for exactly this lagging cursor), the same call
        serves them — behind-the-fold is about missing records, not the
        fold point itself."""
        from knn_tpu.serve import artifact

        model, eng = self._engine(rng, tmp_path, "idx")
        root = _artifact(model, tmp_path, "idx")
        try:
            eng.apply_insert(np.ones((1, 4), np.float32), [0], 0)
            eng._folded_seq = 1  # as a compaction commit would set it
            records, seq = eng.records_since(0)  # epoch retained: serves
            assert [r["seq"] for r in records] == [1] and seq == 1
            for _n, path in artifact.list_epochs(root):
                path.unlink()  # now the pre-fold records are GONE
            with pytest.raises(DataError, match="re-seed"):
                eng.records_since(0)
        finally:
            eng.close()


# -- 2/3. follower + primary over HTTP --------------------------------------


class TestFollowerEndpoints:
    @pytest.fixture
    def follower(self, rng, tmp_path, obs_on):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        idx = _artifact(model, tmp_path, "f")
        rep = _Replica(model, idx, mutable=True,
                       follower_of="http://127.0.0.1:9")
        yield rep, model
        rep.close()

    def test_client_writes_refused_409(self, follower):
        rep, _model = follower
        st, doc = _http(rep.url, "/insert",
                        {"rows": [[1.0] * 4], "labels": [0]})
        assert st == 409 and "read-only follower" in doc["error"]
        st, doc = _http(rep.url, "/delete", {"ids": [0]})
        assert st == 409 and "read-only follower" in doc["error"]

    def test_wal_append_applies_and_surfaces_in_healthz(self, follower):
        rep, _model = follower
        st, doc = _http(rep.url, "/admin/wal-append", {
            "records": [{"seq": 1, "op": "insert", "sid0": 80,
                         "rows": [[1.0] * 4], "values": [1]}],
            "primary_seq": 1,
        })
        assert st == 200
        assert doc["applied_seq"] == 1 and doc["applied"] == 1
        st, h = _http(rep.url, "/healthz")
        assert h["fleet"]["role"] == "follower"
        assert h["fleet"]["applied_seq"] == 1
        assert h["fleet"]["primary_url"] == "http://127.0.0.1:9"
        assert h["mutable"]["seq"] == 1
        # The applied row is VISIBLE to reads through the normal merge.
        st, doc = _http(rep.url, "/kneighbors",
                        {"instances": [[1.0] * 4]})
        assert st == 200 and doc["mutation_seq"] == 1
        assert 80 in doc["indices"][0]

    def test_wal_append_gap_and_divergence_are_typed(self, follower):
        rep, _model = follower
        st, doc = _http(rep.url, "/admin/wal-append", {
            "records": [{"seq": 5, "op": "insert", "sid0": 84,
                         "rows": [[1.0] * 4], "values": [1]}],
        })
        assert st == 409 and doc["applied_seq"] == 0
        rec = {"seq": 1, "op": "insert", "sid0": 80,
               "rows": [[1.0] * 4], "values": [1]}
        assert _http(rep.url, "/admin/wal-append",
                     {"records": [rec]})[0] == 200
        evil = {**rec, "values": [2]}
        st, doc = _http(rep.url, "/admin/wal-append",
                        {"records": [evil]})
        assert st == 409 and doc.get("diverged") is True
        st, doc = _http(rep.url, "/admin/wal-append", {"records": []})
        assert st == 400

    def test_promote_flips_role_in_place(self, follower):
        rep, _model = follower
        st, doc = _http(rep.url, "/admin/promote", {})
        assert st == 200 and doc["role"] == "primary"
        assert doc["promoted_at_seq"] == 0
        # Writes now accepted; wal-append now refused (split brain).
        st, doc = _http(rep.url, "/insert",
                        {"rows": [[1.0] * 4], "labels": [0]})
        assert st == 200 and doc["seq"] == 1
        st, doc = _http(rep.url, "/admin/wal-append", {
            "records": [{"seq": 2, "op": "delete", "sids": [0]}]})
        assert st == 409 and "primary" in doc["error"]
        st, doc = _http(rep.url, "/admin/promote", {})
        assert st == 409 and "already the primary" in doc["error"]

    def test_fleet_off_endpoints_404(self, rng, tmp_path, obs_on):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        rep = _Replica(model, _artifact(model, tmp_path, "p"),
                       mutable=True)
        try:
            assert rep.app.fleet is None
            st, doc = _http(rep.url, "/admin/wal-append",
                            {"records": []})
            assert st == 404
            st, doc = _http(rep.url, "/admin/promote", {})
            assert st == 404
            # wal-since needs only --mutable on, not a fleet role: any
            # replica can export its own log (the rejoin source).
            st, doc = _http(rep.url, "/admin/wal-since?seq=0")
            assert st == 200 and doc["records"] == []
        finally:
            rep.close()


class TestPrimaryShipping:
    def _pair(self, rng, tmp_path, **primary_kw):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        f = _Replica(model, _artifact(model, tmp_path, "f"),
                     mutable=True, follower_of="http://127.0.0.1:9")
        p = _Replica(model, _artifact(model, tmp_path, "p"),
                     mutable=True, replicate_to=[f.url], **primary_kw)
        return model, p, f

    def _wait_seq(self, rep, seq, timeout=10):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if rep.app.mutable.seq >= seq:
                return True
            time.sleep(0.02)
        return False

    def test_acked_writes_ship_and_ack_waits_for_follower(
            self, rng, tmp_path, obs_on):
        model, p, f = self._pair(rng, tmp_path)
        try:
            st, doc = _http(p.url, "/insert",
                            {"rows": [[1.0] * 4, [2.0] * 4],
                             "labels": [0, 1]})
            assert st == 200 and doc["seq"] == 1
            # Semi-sync: by the time the 200 landed, the follower holds
            # the seq (no sleep needed — that is the whole point).
            assert f.app.mutable.seq == 1
            st, h = _http(p.url, "/healthz")
            ship = h["fleet"]["followers"][f.url]
            assert ship["acked_seq"] == 1 and ship["lag"] == 0
            assert ship["state"] == "ok"
        finally:
            p.close()
            f.close()

    def test_ack_timeout_is_typed_applied_true(self, rng, tmp_path,
                                               obs_on):
        """With the follower dead, a write is applied + locally durable
        but CANNOT claim replicated durability: 503 with applied=true,
        never a 200, never a traceback."""
        model, p, f = self._pair(rng, tmp_path,
                                 replicate_ack_timeout_s=0.3)
        try:
            f.kill()
            st, doc = _http(p.url, "/insert",
                            {"rows": [[1.0] * 4], "labels": [0]})
            assert st == 503
            assert doc["applied"] is True and doc["seq"] == 1
            assert "do not re-send" in doc["error"]
            assert p.app.mutable.seq == 1  # applied, WAL-durable
        finally:
            p.close()
            f.app.close()

    def test_shipping_rides_the_fault_point_and_recovers(
            self, rng, tmp_path, obs_on):
        """An injected fleet.wal_ship fault delays the shipment; the
        cursor retries without skipping and the follower converges."""
        model, p, f = self._pair(rng, tmp_path,
                                 replicate_ack_timeout_s=20.0)
        try:
            with faults.inject("fleet.wal_ship=2:io"):
                st, doc = _http(p.url, "/insert",
                                {"rows": [[3.0] * 4], "labels": [0]},
                                timeout=30)
            assert st == 200
            assert self._wait_seq(f, 1)
            a, b = p.app.mutable.snapshot(), f.app.mutable.snapshot()
            np.testing.assert_array_equal(a.features[:a.count],
                                          b.features[:b.count])
        finally:
            p.close()
            f.close()

    def test_promote_then_reship_is_digest_checked_noop(
            self, rng, tmp_path, obs_on):
        """After a promote, the new primary re-ships from cursor 0; the
        overlap is digest-verified and skipped, not re-applied."""
        model, p, f = self._pair(rng, tmp_path)
        try:
            _http(p.url, "/insert", {"rows": [[1.0] * 4], "labels": [0]})
            p.kill()
            st, doc = _http(f.url, "/admin/promote",
                            {"replicate_to": []})
            assert st == 200 and doc["promoted_at_seq"] == 1
            st, doc = _http(f.url, "/insert",
                            {"rows": [[2.0] * 4], "labels": [1]})
            assert st == 200 and doc["seq"] == 2
            assert f.app.mutable.snapshot().count == 2
        finally:
            p.app.close()
            f.close()


# -- 4. the router -----------------------------------------------------------


class TestRouter:
    @pytest.fixture
    def plain_pair(self, rng, tmp_path, obs_on):
        """Two immutable replicas over byte-identical artifact copies
        (same index_version — the fleet deployment shape)."""
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        a_dir = _artifact(model, tmp_path, "a")
        b_dir = tmp_path / "b"
        shutil.copytree(a_dir, b_dir)
        from knn_tpu.serve.artifact import index_version, read_manifest

        version = index_version(read_manifest(a_dir))
        a = _Replica(model, a_dir, index_version=version)
        b = _Replica(model, b_dir, index_version=version)
        yield a, b, model
        a.close()
        b.close()

    def _router(self, urls, **kw):
        from knn_tpu.fleet.router import RouterApp, make_router_server

        kw.setdefault("health_interval_s", 0.1)
        app = RouterApp(urls, **kw)
        server = make_router_server(app)
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        host, port = server.server_address[:2]
        return app, server, f"http://{host}:{port}"

    def _close_router(self, app, server):
        server.shutdown()
        server.server_close()
        app.close()

    def test_reads_survive_a_dead_replica(self, plain_pair):
        a, b, model = plain_pair
        app, server, url = self._router([a.url, b.url])
        try:
            q = model.train_.features[:2].tolist()
            st, doc = _http(url, "/kneighbors", {"instances": q})
            assert st == 200 and "indices" in doc
            a.kill()
            # Every read keeps succeeding: transport failures retry on
            # the surviving replica (passive demotion after the first).
            for _ in range(6):
                st, doc = _http(url, "/kneighbors", {"instances": q})
                assert st == 200, doc
            st, h = _http(url, "/healthz")
            assert st == 200 and h["ready"]
            assert h["replicas"][a.url]["healthy"] is False
            assert h["replicas"][b.url]["healthy"] is True
        finally:
            self._close_router(app, server)
            a.app.close()

    def test_zero_usable_is_typed_503_everywhere(self, plain_pair):
        a, b, model = plain_pair
        app, server, url = self._router([a.url, b.url])
        try:
            a.kill()
            b.kill()
            q = model.train_.features[:1].tolist()
            st, doc = _http(url, "/kneighbors", {"instances": q})
            assert st == 503 and "error" in doc
            st, doc = _http(url, "/insert",
                            {"rows": q, "labels": [0]})
            assert st == 503 and "error" in doc
            st, h = _http(url, "/healthz")
            assert st == 503 and h["ready"] is False
        finally:
            self._close_router(app, server)
            a.app.close()
            b.app.close()

    def test_writes_route_only_to_the_primary(self, rng, tmp_path,
                                              obs_on):
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        # ack="none" on the follower: after its promote its only peer is
        # the dead ex-primary, and this test pins ROUTING, not the
        # semi-sync ack bar (TestPrimaryShipping owns that).
        f = _Replica(model, _artifact(model, tmp_path, "f"),
                     mutable=True, follower_of="http://127.0.0.1:9",
                     replicate_ack="none")
        p = _Replica(model, _artifact(model, tmp_path, "p"),
                     mutable=True, replicate_to=[f.url])
        app, server, url = self._router([f.url, p.url])
        try:
            st, doc = _http(url, "/insert",
                            {"rows": [[1.0] * 4], "labels": [0]})
            assert st == 200 and doc["seq"] == 1
            assert p.app.mutable.seq == 1
            # No primary usable -> typed 503, never a forward to a
            # follower.
            p.kill()
            app.set.poll_once()
            st, doc = _http(url, "/insert",
                            {"rows": [[1.0] * 4], "labels": [0]})
            assert st == 503 and "primary" in doc["error"]
            st, doc = _http(url, "/admin/promote", {})
            assert st == 200 and doc["replica"] == f.url
            st, doc = _http(url, "/insert",
                            {"rows": [[1.0] * 4], "labels": [0]})
            assert st == 200 and doc["seq"] == 2
        finally:
            self._close_router(app, server)
            p.app.close()
            f.close()

    def test_coordinated_reload_is_all_or_nothing(self, rng, tmp_path,
                                                  obs_on):
        """Replica B refuses the reload (mutable serving disables it):
        the router must roll A back — both stay on v0 — and report
        typed rolled_back. With B gone from the set, the reload flips
        everyone."""
        model = KNNClassifier(k=3, engine="xla").fit(_problem(rng))
        a_dir = _artifact(model, tmp_path, "a")
        new_dir = tmp_path / "new"
        save_index(model, new_dir)
        from knn_tpu.serve.artifact import index_version, read_manifest

        v0 = index_version(read_manifest(a_dir))
        a = _Replica(model, a_dir, index_version=v0)
        b = _Replica(model, _artifact(model, tmp_path, "bm"),
                     index_version=v0, mutable=True)
        app, server, url = self._router([a.url, b.url])
        try:
            st, doc = _http(url, "/admin/reload",
                            {"index": str(new_dir)}, timeout=120)
            assert st == 502 and doc["rolled_back"] is True
            assert doc["flipped_then_rolled_back"] == [a.url]
            st, h = _http(a.url, "/healthz")
            assert h["index_version"] == v0  # rolled back
        finally:
            self._close_router(app, server)
        app2, server2, url2 = self._router([a.url])
        try:
            st, doc = _http(url2, "/admin/reload",
                            {"index": str(new_dir)}, timeout=120)
            assert st == 200 and doc["replicas"] == 1
            st, h = _http(a.url, "/healthz")
            assert h["index_version"] == doc["index_version"] != v0
        finally:
            self._close_router(app2, server2)
            a.close()
            b.close()

    def test_forward_fault_point_retries_on_another_replica(
            self, plain_pair):
        """An injected fleet.forward fault on the first attempt is a
        transport failure: the read must answer 200 from the other
        replica, not surface the fault."""
        a, b, model = plain_pair
        app, server, url = self._router([a.url, b.url])
        try:
            q = model.train_.features[:1].tolist()
            with faults.inject("fleet.forward=once:io"):
                st, doc = _http(url, "/kneighbors", {"instances": q})
            assert st == 200, doc
        finally:
            self._close_router(app, server)

    def test_hedge_delay_needs_evidence(self, plain_pair):
        a, b, _model = plain_pair
        app, server, url = self._router([a.url, b.url], hedge="auto")
        try:
            assert app.hedge_delay_s() is None  # <50 observations
            for ms in range(60):
                app._note_latency(float(ms))
            d = app.hedge_delay_s()
            assert d is not None and 0.0 < d <= 0.06
            app2 = type(app)([a.url], hedge="25")
            assert app2.hedge_delay_s() == 0.025
            app2.close()
            with pytest.raises(ValueError):
                type(app)([a.url], hedge="-3")
        finally:
            self._close_router(app, server)

    def test_unknown_route_and_bad_body_are_typed(self, plain_pair):
        a, b, _model = plain_pair
        app, server, url = self._router([a.url, b.url])
        try:
            st, doc = _http(url, "/nope", {"x": 1})
            assert st == 404 and "error" in doc
            st, doc = _http(url, "/healthz")
            assert st == 200
        finally:
            self._close_router(app, server)
