"""Distance-metric extensions (manhattan / chebyshev / cosine).

The reference hard-codes squared Euclidean (main.cpp:14-23); these are
framework extensions, so the parity oracle is this repo's own NumPy
implementation (`backends/oracle.py::_metric_dists`, formula-matched to
`ops/distance.py`). Integer-grid fixtures make manhattan/chebyshev exact in
float32, so prediction equality is required, ties included.
"""

import numpy as np
import pytest

from knn_tpu.backends.oracle import knn_oracle
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier, KNNRegressor
from knn_tpu.ops.distance import resolve_form

EXACT_METRICS = ["manhattan", "chebyshev"]
ALL_METRICS = EXACT_METRICS + ["cosine"]


def _grid_problem(rng, n=500, q=70, d=7, c=8):
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, q // 2, replace=False)],
         rng.integers(0, 4, (q - q // 2, d)).astype(np.float32)]
    )
    return train_x, train_y, test_x, c


class TestResolveForm:
    def test_euclidean_passes_precision_through(self):
        assert resolve_form("fast", "euclidean") == "fast"
        assert resolve_form("exact") == "exact"

    def test_metric_maps_to_its_form(self):
        assert resolve_form("exact", "manhattan") == "manhattan"
        assert resolve_form("auto", "cosine") == "cosine"

    def test_unknown_metric_rejected(self):
        with pytest.raises(ValueError, match="unknown metric"):
            resolve_form("exact", "mahalanobis")

    def test_precision_incompatible_with_metric(self):
        with pytest.raises(ValueError, match="single implementation"):
            resolve_form("bf16", "manhattan")


class TestMetricParity:
    @pytest.mark.parametrize("metric", EXACT_METRICS)
    @pytest.mark.parametrize("backend", ["tpu", "tpu-sharded", "tpu-train-sharded", "tpu-ring"])
    def test_backend_matches_oracle(self, rng, metric, backend):
        train_x, train_y, test_x, c = _grid_problem(rng)
        want = knn_oracle(train_x, train_y, test_x, 5, c, metric=metric)
        model = KNNClassifier(k=5, backend=backend, metric=metric).fit(
            Dataset(train_x, train_y)
        )
        got = model.predict(Dataset(test_x, np.zeros(len(test_x), np.int32)))
        np.testing.assert_array_equal(got, want)

    def test_cosine_matches_oracle_on_separated_data(self, rng):
        # Cosine distances round differently across backends; use direction
        # clusters with wide angular gaps so predictions are rounding-robust.
        c = 4
        angles = {0: 0.0, 1: np.pi / 2, 2: np.pi, 3: 3 * np.pi / 2}
        train_y = rng.integers(0, c, 300).astype(np.int32)
        radii = rng.uniform(0.5, 3.0, 300).astype(np.float32)
        jitter = rng.uniform(-0.1, 0.1, 300)
        theta = np.array([angles[y] for y in train_y]) + jitter
        train_x = np.stack(
            [radii * np.cos(theta), radii * np.sin(theta)], axis=1
        ).astype(np.float32)
        test_theta = rng.uniform(0, 2 * np.pi, 50)
        test_x = np.stack(
            [np.cos(test_theta), np.sin(test_theta)], axis=1
        ).astype(np.float32)
        want = knn_oracle(train_x, train_y, test_x, 7, c, metric="cosine")
        model = KNNClassifier(k=7, metric="cosine").fit(Dataset(train_x, train_y))
        got = model.predict(Dataset(test_x, np.zeros(50, np.int32)))
        assert (got == want).mean() >= 0.96  # rounding may flip knife-edge rows

    @pytest.mark.parametrize("metric", EXACT_METRICS)
    def test_metric_changes_neighbors(self, rng, metric):
        # Sanity: the metric genuinely alters retrieval vs euclidean.
        train_x = np.array([[0, 0], [3, 3], [0, 5]], np.float32)
        test_x = np.array([[2.0, 2.0]], np.float32)
        model_e = KNNClassifier(k=1).fit(Dataset(train_x, np.arange(3, dtype=np.int32)))
        model_m = KNNClassifier(k=1, metric=metric).fit(
            Dataset(train_x, np.arange(3, dtype=np.int32))
        )
        _, idx_e = model_e.kneighbors(Dataset(test_x, np.zeros(1, np.int32)))
        _, idx_m = model_m.kneighbors(Dataset(test_x, np.zeros(1, np.int32)))
        # euclidean nearest to (2,2) is (3,3); manhattan ties (0,0) d=4 vs
        # (3,3) d=2 -> still (3,3); chebyshev: (3,3) d=1. All well-defined:
        assert idx_e[0, 0] == 1
        assert idx_m.shape == (1, 1)

    def test_regressor_supports_metric(self, rng):
        train_x, _, test_x, _ = _grid_problem(rng, n=200, q=20)
        targets = rng.normal(0, 5, 200).astype(np.float32)
        train = Dataset(train_x, np.zeros(200, np.int32), raw_targets=targets)
        test = Dataset(test_x, np.zeros(20, np.int32))
        got = KNNRegressor(k=3, metric="manhattan").fit(train).predict(test)
        d = np.abs(test_x[:, None, :] - train_x[None, :, :]).sum(-1)
        order = np.lexsort(
            (np.broadcast_to(np.arange(200), d.shape), d), axis=1
        )[:, :3]
        np.testing.assert_allclose(got, targets[order].mean(1), rtol=1e-6)


class TestMetricErrors:
    def test_native_backend_rejects_metric(self, small):
        train, test = small
        from knn_tpu.backends import available_backends, get_backend

        if "native" not in available_backends():
            pytest.skip("native backend unavailable")
        with pytest.raises(ValueError, match="euclidean only"):
            get_backend("native")(train, test, 1, metric="manhattan")

    def test_cli_metric_flag(self, tmp_path, small_paths):
        from knn_tpu.cli import run
        import io

        train_p, test_p = small_paths
        out = io.StringIO()
        rc = run([train_p, test_p, "1", "--backend", "oracle",
                  "--metric", "manhattan"], stdout=out)
        assert rc == 0
        assert "Accuracy was" in out.getvalue()

    def test_cli_metric_rejected_for_native(self, small_paths):
        from knn_tpu.backends import available_backends
        from knn_tpu.cli import run

        if "native" not in available_backends():
            pytest.skip("native backend unavailable")
        train_p, test_p = small_paths
        rc = run([train_p, test_p, "1", "--backend", "native",
                  "--metric", "cosine"])
        assert rc == 1

    def test_cosine_nan_features_excluded(self):
        # NaN-feature rows must follow the NaN -> +inf policy under cosine
        # too (a bare `denom > 0` guard would leave them at d=1.0, ranking
        # them ahead of anti-correlated valid neighbors).
        train_x = np.array([[1.0, 0.0], [np.nan, 1.0], [-1.0, 0.0]], np.float32)
        train_y = np.array([0, 1, 2], np.int32)
        test_x = np.array([[1.0, 0.0]], np.float32)
        want = knn_oracle(train_x, train_y, test_x, 2, 3, metric="cosine")
        model = KNNClassifier(k=2, metric="cosine").fit(Dataset(train_x, train_y))
        _, idx = model.kneighbors(Dataset(test_x, np.zeros(1, np.int32)))
        # Neighbors: row 0 (d=0) then row 2 (d=2); NaN row 1 must be last.
        np.testing.assert_array_equal(idx[0], [0, 2])
        got = model.predict(Dataset(test_x, np.zeros(1, np.int32)))
        np.testing.assert_array_equal(got, want)

    def test_model_rejects_unknown_metric(self):
        with pytest.raises(ValueError, match="unknown metric"):
            KNNClassifier(k=1, metric="hamming")
        with pytest.raises(ValueError, match="unknown metric"):
            KNNRegressor(k=1, metric="hamming")
