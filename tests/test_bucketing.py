"""Shape-bucketed compiled batches, continuous batching, and the
exact-match result cache (the PR 12 serving hot-path rebuild).

Pinned contracts:

- ``models/knn.query_padded_rows`` is THE one definition (pad,
  executable-cache key, accounting) and resolves buckets exactly;
- bucketed dispatch is **bit-identical** to the unbucketed path across
  rungs x kinds x mutable view on/off x cache hit/miss vs cold;
- continuous batching tops a closed batch up to its bucket boundary,
  never past it;
- the result cache is correct by construction between version/sequence
  points: a hot swap clears it, a mutation's sequence-point move makes
  every stale key unreachable;
- an OOM-halved ``max_batch`` re-clamps onto already-compiled ladder
  shapes (never a never-compiled one);
- the what-if simulator's occupancy/waste for a bucket policy match the
  live bucketed batcher on the committed replay fixture.
"""

from __future__ import annotations

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models import knn as knn_mod
from knn_tpu.models.knn import KNNClassifier, KNNRegressor
from knn_tpu.obs.accounting import padded_query_rows
from knn_tpu.resilience import faults
from knn_tpu.serve.batcher import MicroBatcher
from knn_tpu.serve.cache import ResultCache, query_digest
from knn_tpu.utils.padding import pad_axis_to_size


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


def _problem(rng, n=160, d=6, q=12, classes=4):
    train = Dataset(
        rng.normal(0.0, 2.0, (n, d)).astype(np.float32),
        rng.integers(0, classes, n).astype(np.int32),
    )
    test = rng.normal(0.0, 2.0, (q, d)).astype(np.float32)
    return train, test


# ---------------------------------------------------------------------------
# The one padded-shape definition


class TestQueryBucketLadder:
    def test_legacy_quantum_without_ladder(self):
        assert knn_mod.query_buckets() is None
        assert knn_mod.query_padded_rows(1) == 128
        assert knn_mod.query_padded_rows(128) == 128
        assert knn_mod.query_padded_rows(129) == 256
        assert knn_mod.query_padded_rows(0) == 0

    def test_ladder_pads_to_smallest_bucket(self):
        with knn_mod.query_bucket_ladder((16, 32, 64)):
            assert knn_mod.query_padded_rows(1) == 16
            assert knn_mod.query_padded_rows(16) == 16
            assert knn_mod.query_padded_rows(17) == 32
            assert knn_mod.query_padded_rows(64) == 64
            # Past the top bucket: multiples of it (bounded shape set).
            assert knn_mod.query_padded_rows(65) == 128
            assert knn_mod.query_padded_rows(129) == 192

    def test_context_manager_restores_even_nested(self):
        with knn_mod.query_bucket_ladder((8,)):
            assert knn_mod.query_padded_rows(3) == 8
            with knn_mod.query_bucket_ladder((4,)):
                assert knn_mod.query_padded_rows(3) == 4
            assert knn_mod.query_padded_rows(3) == 8
        assert knn_mod.query_buckets() is None

    def test_normalize_validation(self):
        assert knn_mod.normalize_buckets([32, 8, 8, 16]) == (8, 16, 32)
        for bad in ([], [0, 8], [-1], ["x"], None):
            with pytest.raises(ValueError):
                knn_mod.normalize_buckets(bad)

    def test_accounting_shares_the_definition(self):
        # The PR-8 hardening contract: padded-row accounting resolves
        # from the same helper as the pad and the executable-cache key.
        with knn_mod.query_bucket_ladder((8, 32)):
            assert padded_query_rows("xla", 3) == 8
            assert padded_query_rows("xla", 9) == 32
            assert padded_query_rows("oracle", 9) == 9
        assert padded_query_rows("xla", 3) == 128

    def test_pad_axis_to_size(self):
        a = np.ones((3, 2), np.float32)
        out = pad_axis_to_size(a, 5)
        assert out.shape == (5, 2) and out[3:].sum() == 0
        assert pad_axis_to_size(a, 3) is a
        with pytest.raises(ValueError):
            pad_axis_to_size(a, 2)

    def test_retrieval_executable_keys_on_bucket(self, rng, obs_on):
        # Two batch sizes inside one bucket share one executable; a size
        # in the next bucket is a fresh compile — the cache counters see
        # exactly that.
        from knn_tpu.obs import devprof

        train, test = _problem(rng, q=12)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        with knn_mod.query_bucket_ladder((4, 8, 16)):
            devprof.reset_state()
            model.kneighbors(Dataset(test[:2], np.zeros(2, np.int32)))
            model.kneighbors(Dataset(test[:3], np.zeros(3, np.int32)))
            model.kneighbors(Dataset(test[:7], np.zeros(7, np.int32)))
            c = devprof.executable_cache_summary()
        assert c["misses"] == 2  # bucket 4 once, bucket 8 once
        assert c["hits"] == 1    # 3 rows re-rides the 4-row executable


# ---------------------------------------------------------------------------
# Bit-identity: buckets x kinds x rungs x mutable x cache


class TestBucketedBitIdentity:
    @pytest.mark.parametrize("family", ["classifier", "regressor"])
    def test_bucketed_matches_unbucketed_both_kinds(self, rng, family):
        train, test = _problem(rng)
        if family == "classifier":
            model = KNNClassifier(k=3).fit(train)
        else:
            model = KNNRegressor(k=3).fit(train)
        plain = MicroBatcher(model, max_batch=16, max_wait_ms=0.0)
        try:
            want_k = plain.kneighbors(test, timeout=60)
            want_p = plain.predict(test, timeout=60)
            want_p1 = plain.predict(test[0], timeout=60)
        finally:
            plain.close()
        with knn_mod.query_bucket_ladder((4, 8, 16)):
            b = MicroBatcher(model, max_batch=16, max_wait_ms=0.0,
                             buckets=(4, 8, 16), result_cache_rows=128)
            try:
                for _ in range(2):  # second pass = cache hits
                    got_k = b.kneighbors(test, timeout=60)
                    np.testing.assert_array_equal(got_k[0], want_k[0])
                    np.testing.assert_array_equal(got_k[1], want_k[1])
                    np.testing.assert_array_equal(
                        b.predict(test, timeout=60), want_p)
                    np.testing.assert_array_equal(
                        b.predict(test[0], timeout=60), want_p1)
                assert b.cache.stats()["hits"] > 0
            finally:
                b.close()

    def test_degraded_rungs_stay_bit_identical_bucketed(self, rng):
        # Every-rung coverage: a persistent fast-rung fault walks the
        # ladder (fast -> xla -> oracle); each degraded answer must equal
        # the healthy one, bucketed, with the cache on (cold + hit).
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="auto").fit(train)
        want = model.predict(Dataset(test, np.zeros(len(test), np.int32)))
        with knn_mod.query_bucket_ladder((4, 8, 16)):
            b = MicroBatcher(model, max_batch=16, max_wait_ms=0.0,
                             buckets=(4, 8, 16), result_cache_rows=128)
            try:
                with faults.inject("serve.dispatch=always"):
                    got = b.predict(test, timeout=60)
                np.testing.assert_array_equal(got, want)
                # Degraded answers are NOT cached (rung != primary): the
                # next healthy dispatch is a fresh primary-rung answer.
                assert b.cache.stats()["entries"] == 0
                np.testing.assert_array_equal(
                    b.predict(test, timeout=60), want)
                assert b.cache.stats()["entries"] == 1
                h = b.submit(test, "predict")
                np.testing.assert_array_equal(h.result(timeout=60), want)
                assert h.meta.get("cache") == "hit"
            finally:
                b.close()

    def test_mutable_view_bucketed_matches_unbucketed(self, rng, tmp_path):
        # Two byte-identical artifact stacks, identical mutations; the
        # bucketed+cached one must answer bit-identically to the plain
        # one at every sequence point.
        import shutil

        from knn_tpu.mutable.engine import MutableEngine
        from knn_tpu.serve import artifact

        train, test = _problem(rng, n=80, q=6)
        model = KNNClassifier(k=3).fit(train)
        artifact.save_index(model, tmp_path / "a")
        shutil.copytree(tmp_path / "a", tmp_path / "b")

        def build(d, bucketed):
            m = artifact.load_index(d)
            eng = MutableEngine(m, d, version="v1")
            kw = dict(max_batch=8, max_wait_ms=0.0, mutable=eng,
                      index_version="v1")
            if bucketed:
                kw.update(buckets=(2, 4, 8), result_cache_rows=64)
            return MicroBatcher(m, **kw), eng

        plain, eng_a = build(tmp_path / "a", False)
        with knn_mod.query_bucket_ladder((2, 4, 8)):
            bucketed, eng_b = build(tmp_path / "b", True)
            try:
                ins = rng.normal(0.0, 2.0, (2, test.shape[1])).astype(
                    np.float32)
                for bat in (plain, bucketed):
                    bat.submit_mutation(
                        "insert", {"rows": ins, "values": [1, 2]}
                    ).result(timeout=60)
                    bat.submit_mutation(
                        "delete", {"ids": [0]}).result(timeout=60)
                for _ in range(2):  # pass 2 = cache hits on the bucketed side
                    hk_p = plain.submit(test, "kneighbors")
                    hk_b = bucketed.submit(test, "kneighbors")
                    wk, gk = hk_p.result(timeout=60), hk_b.result(timeout=60)
                    assert hk_p.meta["mutation_seq"] == hk_b.meta[
                        "mutation_seq"]
                    np.testing.assert_array_equal(gk[0], wk[0])
                    np.testing.assert_array_equal(gk[1], wk[1])
                    np.testing.assert_array_equal(
                        bucketed.predict(test, timeout=60),
                        plain.predict(test, timeout=60))
                assert bucketed.cache.stats()["hits"] > 0
            finally:
                plain.close()
                bucketed.close()
                eng_a.close()
                eng_b.close()

    def test_ivf_rung_bucketed_matches_unbucketed(self, rng, tmp_path):
        from knn_tpu.index.ivf import IVFIndex, IVFServing

        train, test = _problem(rng, n=240, q=8)
        model = KNNClassifier(k=3).fit(train)
        model.ivf_ = IVFIndex.build(train.features, 8, seed=0)

        def serving():
            return IVFServing(2, 8)

        plain = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                             ivf=serving())
        try:
            want = plain.kneighbors(test, timeout=60)
        finally:
            plain.close()
        with knn_mod.query_bucket_ladder((2, 4, 8)):
            b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                             ivf=serving(), buckets=(2, 4, 8),
                             result_cache_rows=64)
            try:
                for _ in range(2):
                    got = b.kneighbors(test, timeout=60)
                    np.testing.assert_array_equal(got[0], want[0])
                    np.testing.assert_array_equal(got[1], want[1])
                assert b.cache.stats()["hits"] > 0
            finally:
                b.close()


# ---------------------------------------------------------------------------
# Continuous batching


class _HeldBatcher(MicroBatcher):
    """A batcher whose worker never runs — tests drive _collect/_dispatch
    deterministically on the test thread."""

    def _supervise(self):  # pragma: no cover — intentionally inert
        return


class TestContinuousBatching:
    def test_topup_admits_up_to_bucket_boundary(self, rng, obs_on):
        train, test = _problem(rng, q=8)
        model = KNNClassifier(k=3).fit(train)
        want = model.predict(Dataset(test, np.zeros(len(test), np.int32)))
        with knn_mod.query_bucket_ladder((4, 8)):
            b = _HeldBatcher(model, max_batch=8, max_wait_ms=0.0,
                             buckets=(4, 8))
            handles = [b.submit(test[0], "predict")]
            batch = b._collect()
            assert len(batch) == 1
            # Arrivals AFTER the window closed, BEFORE dispatch: the
            # batch's bucket is 4, so exactly 3 more single-row requests
            # ride free — the rest stay queued for the next window.
            handles += [b.submit(test[i], "predict") for i in range(1, 6)]
            b._dispatch(batch)
            for i in range(4):
                np.testing.assert_array_equal(
                    handles[i].result(timeout=5), want[i])
            assert b.pending_rows() == 2  # 2 requests past the boundary
            for h in handles[4:]:
                assert h.meta.get("rung") is None  # untouched, still queued
            assert obs_on.counter(
                "knn_serve_topup_rows_total").value == 3
            b.close(timeout=0.1)

    def test_no_topup_without_room(self, rng):
        from knn_tpu.resilience.errors import DeadlineExceededError

        train, test = _problem(rng, q=8)
        model = KNNClassifier(k=3).fit(train)
        with knn_mod.query_bucket_ladder((4, 8)):
            b = _HeldBatcher(model, max_batch=8, max_wait_ms=0.0,
                             buckets=(4, 8))
            h1 = b.submit(test[:4], "predict")  # exactly bucket 4
            batch = b._collect()
            h2 = b.submit(test[4], "predict")
            b._dispatch(batch)
            h1.result(timeout=5)
            assert b.pending_rows() == 1  # no free slot below the boundary
            with pytest.raises(DeadlineExceededError):
                h2.result(timeout=0.05)
            b.close(timeout=0.1)


# ---------------------------------------------------------------------------
# The result cache


class TestResultCache:
    def test_lru_evicts_by_rows(self):
        c = ResultCache(4)
        mk = lambda rows: (np.zeros((rows, 3)), np.zeros((rows, 3), np.int32))
        for n, rows in (("a", 2), ("b", 2)):
            d, i = mk(rows)
            c.put((n,), d, i, "fast")
        assert c.stats()["rows"] == 4
        d, i = mk(2)
        c.put(("c",), d, i, "fast")  # evicts the LRU entry "a"
        assert c.get(("a",)) is None
        assert c.get(("c",)) is not None
        s = c.stats()
        assert s["rows"] == 4 and s["evictions"] == 1

    def test_oversized_entry_not_cached(self):
        c = ResultCache(2)
        c.put(("big",), np.zeros((3, 3)), np.zeros((3, 3), np.int32), "fast")
        assert c.stats()["entries"] == 0

    def test_digest_is_bit_exact(self):
        a = np.array([[1.0, -0.0]], np.float32)
        b = np.array([[1.0, 0.0]], np.float32)
        assert query_digest(a) != query_digest(b)  # -0.0 is a different row
        assert query_digest(a) == query_digest(a.copy())

    def test_swap_model_clears_cache(self, rng):
        train, test = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                         index_version="v1", result_cache_rows=64)
        try:
            b.predict(test[0], timeout=60)
            assert b.cache.stats()["entries"] == 1
            b.swap_model(model, "v2")  # the hot-reload path
            assert b.cache.stats()["entries"] == 0
            h = b.submit(test[0], "predict")
            h.result(timeout=60)
            # Fresh version, fresh key: a miss, never a stale v1 answer.
            assert h.meta.get("cache") != "hit"
            assert h.meta["index_version"] == "v2"
        finally:
            b.close()

    def test_mutation_seq_invalidates_by_key(self, rng, tmp_path):
        from knn_tpu.mutable.engine import MutableEngine
        from knn_tpu.serve import artifact

        train, test = _problem(rng, n=60, q=4)
        model = KNNClassifier(k=3).fit(train)
        artifact.save_index(model, tmp_path / "idx")
        m = artifact.load_index(tmp_path / "idx")
        eng = MutableEngine(m, tmp_path / "idx", version="v1")
        b = MicroBatcher(m, max_batch=8, max_wait_ms=0.0, mutable=eng,
                         index_version="v1", result_cache_rows=64)
        try:
            q0 = test[0]
            h0 = b.submit(q0, "kneighbors")
            d0, i0 = h0.result(timeout=60)
            seq0 = h0.meta["mutation_seq"]
            # Insert the query row itself: the new delta row becomes the
            # exact-match nearest neighbor — a stale cached answer would
            # be visibly wrong.
            b.submit_mutation("insert", {"rows": q0[None, :],
                                         "values": [1]}).result(timeout=60)
            h1 = b.submit(q0, "kneighbors")
            d1, i1 = h1.result(timeout=60)
            assert h1.meta["mutation_seq"] == seq0 + 1
            assert h1.meta.get("cache") != "hit"  # new seq point = new key
            assert d1[0, 0] == 0.0  # the freshly inserted exact match won
            assert not np.array_equal(i1, i0)
            # Same seq point again: NOW it hits, with the merged answer.
            h2 = b.submit(q0, "kneighbors")
            d2, i2 = h2.result(timeout=60)
            assert h2.meta.get("cache") == "hit"
            np.testing.assert_array_equal(d2, d1)
            np.testing.assert_array_equal(i2, i1)
        finally:
            b.close()
            eng.close()

    def test_cache_counters_exported(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                         result_cache_rows=64)
        try:
            b.predict(test[0], timeout=60)
            b.predict(test[0], timeout=60)
        finally:
            b.close()
        assert obs_on.counter("knn_cache_hits_total").value == 1
        assert obs_on.counter("knn_cache_misses_total").value == 1


# ---------------------------------------------------------------------------
# OOM halving x bucket ladder


class TestOOMHalvingReclamp:
    def test_halved_cap_redispatches_on_compiled_buckets(self, rng, obs_on):
        train, test = _problem(rng, q=8)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        want = model.predict(Dataset(test, np.zeros(len(test), np.int32)))
        with knn_mod.query_bucket_ladder((2, 4, 8)):
            from knn_tpu.serve.artifact import warmup

            warmup(model, batch_sizes=(2, 4, 8), kinds=("predict",))
            b = MicroBatcher(model, max_batch=8, max_wait_ms=1.0,
                             buckets=(2, 4, 8))
            try:
                with faults.inject("serve.dispatch=once:oom"):
                    got = b.predict(test, timeout=60)
                assert b.max_batch == 4  # halved in place
                np.testing.assert_array_equal(got, want)
                # The chunked re-dispatch re-clamped onto LADDER shapes:
                # 8 rows at cap 4 = two 4-row chunks, each padding to the
                # already-compiled 4-row bucket — padded accounting says
                # exactly that (2 chunks x 4 compiled rows).
                from knn_tpu.obs.accounting import dispatch_padded_rows

                assert dispatch_padded_rows(model, "fast", 8,
                                            b.max_batch) == 8
                got2 = b.predict(test, timeout=60)  # post-halve steady state
                np.testing.assert_array_equal(got2, want)
            finally:
                b.close()


# ---------------------------------------------------------------------------
# What-if simulator <-> live bucketed batcher parity


class TestWhatifLiveParity:
    @pytest.mark.slow
    def test_simulator_matches_live_occupancy_and_waste(self):
        """Replay the committed fixture through the REAL bucketed batcher
        and hold the simulator's predicted occupancy/waste for the same
        policy to the measured values (the replay-gate agreement
        contract, here for the two shape metrics the bucket ladder
        exists to move)."""
        from tests import fixtures
        from knn_tpu.obs import whatif
        from knn_tpu.obs.capacity import CapacityTracker
        from knn_tpu.obs.replay import replay_workload
        from knn_tpu.obs.workload import load_workload
        from knn_tpu.serve.artifact import warmup

        wl = load_workload(fixtures.REPLAY_WORKLOAD_DIR)
        policy = wl.manifest["policy"]
        buckets = (2, 4, 8, 16)
        model = fixtures.replay_fixture_model()
        with knn_mod.query_bucket_ladder(buckets):
            warmup(model, batch_sizes=(1,) + buckets, kinds=("predict",))
            capacity = CapacityTracker(policy["max_batch"])
            b = MicroBatcher(
                model, max_batch=policy["max_batch"],
                max_wait_ms=policy["max_wait_ms"],
                index_version=fixtures.REPLAY_FIXTURE_VERSION,
                capacity=capacity, buckets=buckets,
            )
            try:
                v = replay_workload(wl, batcher=b, speed=1.0,
                                    verify="off")
            finally:
                b.close()
            cap = capacity.export()
        assert v["measured"]["errors"] == 0
        fit = cap["dispatch_model"]
        sim = whatif.simulate(
            wl.arrivals(), max_batch=policy["max_batch"],
            max_wait_ms=policy["max_wait_ms"],
            a_ms=fit["a_ms"] or 1.0, b_ms_per_row=fit["b_ms_per_row"] or 0.0,
            buckets=buckets,
        )
        # The same definition on both sides (rows / compiled bucket), so
        # the agreement band is about batch-formation timing jitter, not
        # semantics.
        assert abs(sim["occupancy_mean"] - cap["occupancy_mean"]) <= 0.25
        assert abs(sim["padded_row_waste_ratio"]
                   - cap["padded_row_waste_ratio"]) <= 0.2
