"""Mutable-index contract tests (docs/INDEXES.md §Mutable tier).

The load-bearing claims, in dependency order:

1. **Merge correctness** — base+delta+tombstone retrieval equals a brute-
   force lexicographic top-k over the live view's full candidate matrix,
   under THE shared (distance, index) contract (models/ordering.py), with
   tombstone k-coverage widening so answers never come up short.
2. **Empty-view bit-identity** — a mutable-on server with no mutations
   runs the EXACT immutable ladder: ``_rungs`` returns the same closures
   (not wrappers), and every rung's bytes match the mutable-off answer.
3. **Durability** — every acknowledged mutation is WAL-appended + flushed
   before the ack; a rebuilt engine replays to the identical view; a torn
   final record (crash mid-append, never acked) is dropped; corruption
   anywhere else is a typed :class:`DataError`.
4. **Compaction** — the fold is a deterministic function of the
   acknowledged history; the swap+rebase is atomic to dispatch snapshots;
   any failure before the CURRENT.json commit leaves the old generation
   serving with zero acknowledged writes lost.
5. **HTTP mapping** — /insert, /delete, /admin/compact carry the typed
   status contract (404 off, 400 malformed, 409 conflict, 429 full,
   200 = durable + visible).
"""

import json
import threading

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier, KNNRegressor
from knn_tpu.models.ordering import lexicographic_topk
from knn_tpu.mutable.compact import CompactionInProgress, Compactor, fold
from knn_tpu.mutable.engine import MutableEngine
from knn_tpu.mutable.state import (
    MutableView,
    MutationConflict,
    merge_candidates,
    merged_oracle_kneighbors,
    validate_insert,
)
from knn_tpu.resilience.errors import DataError, OverloadError
from knn_tpu.serve import artifact
from knn_tpu.serve.artifact import load_index, save_index
from knn_tpu.serve.batcher import MicroBatcher


def _problem(rng, n=200, q=24, d=5, c=4):
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)  # grid -> ties
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, q // 2, replace=False)],
         rng.integers(0, 4, (q - q // 2, d)).astype(np.float32)]
    )
    return Dataset(train_x, train_y), test_x


def _root(model, tmp_path):
    """A mutable engine needs a real artifact directory (its WAL and
    generations live inside one); reuse it across engines in a test."""
    out = tmp_path / "idx"
    if not (out / "manifest.json").exists():
        save_index(model, out)
    return out


def _engine(model, root, **kw):
    kw.setdefault("delta_cap", 256)
    return MutableEngine(model, root, **kw)


def _brute_force_view(model, view, queries, k):
    """Independent re-derivation of the merge contract: full distance
    matrix over [base; delta], tombstoned positional ids masked, one
    lexicographic top-k."""
    from knn_tpu.backends.oracle import _metric_dists

    train = model.train_
    full = np.concatenate(
        [train.features, np.asarray(view.features[:view.count])])
    d = np.asarray(_metric_dists(np.asarray(queries, np.float32), full,
                                 model.metric), np.float64)
    np.nan_to_num(d, copy=False, nan=np.inf)
    ids = np.broadcast_to(np.arange(full.shape[0], dtype=np.int64),
                          d.shape).copy()
    for p in view.tomb_pos:
        d[:, p] = np.inf
        ids[:, p] = view.sentinel
    return lexicographic_topk(d, ids, k)


class TestMergeContract:
    def test_merged_oracle_matches_brute_force(self, rng, tmp_path):
        """Random inserts + deletes: the production merge (widening path
        included) equals the brute-force lexicographic truth."""
        train, test_x = _problem(rng)
        model = KNNClassifier(k=4, engine="xla").fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            for lo in range(0, 24, 6):
                eng.apply_insert(
                    rng.integers(0, 4, (6, 5)).astype(np.float32),
                    rng.integers(0, 4, 6), 0)
            # Delete base rows that ARE someone's neighbor (forces the
            # widening) plus a couple of delta rows.
            _, base_i = model.kneighbors(
                Dataset(test_x, np.zeros(len(test_x), np.int32)))
            victims = sorted({int(base_i[0, 0]), int(base_i[3, 0]),
                              int(base_i[7, 1]), 200 + 2, 200 + 11})
            eng.apply_delete(victims, 0)
            view = eng.snapshot()
            got_d, got_i = merged_oracle_kneighbors(model, view, test_x)
            want_d, want_i = _brute_force_view(model, view, test_x, model.k)
            np.testing.assert_array_equal(got_i, want_i)
            np.testing.assert_array_equal(
                got_d.astype(np.float32), want_d.astype(np.float32))
            for v in victims:
                assert not (got_i == v).any()
        finally:
            eng.close()

    def test_tie_order_base_beats_delta(self, rng, tmp_path):
        """A delta row duplicating a base row loses the distance tie to
        the lower positional id — THE (distance, index) contract."""
        train, test_x = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            dup = train.features[17].copy()
            eng.apply_insert(dup[None, :], [1], 0)
            got_d, got_i = merged_oracle_kneighbors(
                model, eng.snapshot(), dup[None, :])
            row = got_i[0].tolist()
            assert 17 in row and 200 in row
            assert row.index(17) < row.index(200)
            assert got_d[0][row.index(200)] == 0.0
        finally:
            eng.close()

    def test_widening_never_returns_short_or_dead(self, rng, tmp_path):
        """Delete a query's ENTIRE base top-k: the answer still has k
        live rows and none of the dead ones."""
        train, test_x = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            q = test_x[:1]
            _, base_i = model.kneighbors(Dataset(q, np.zeros(1, np.int32)))
            victims = [int(v) for v in base_i[0]]
            eng.apply_delete(victims, 0)
            view = eng.snapshot()
            got_d, got_i = merged_oracle_kneighbors(model, view, q)
            assert got_i.shape == (1, 3)
            assert np.isfinite(got_d).all()
            assert not np.isin(got_i, victims).any()
            want_d, want_i = _brute_force_view(model, view, q, model.k)
            np.testing.assert_array_equal(got_i, want_i)
        finally:
            eng.close()

    def test_nan_query_masked_slots_rank_last(self, rng, tmp_path):
        """A NaN query makes every real distance +inf; masked slots must
        still rank after real rows (the sentinel-id rule), so the answer
        is live rows in index order."""
        train, _ = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            eng.apply_delete([0, 1], 0)
            q = np.full((1, 5), np.nan, np.float32)
            _, got_i = merged_oracle_kneighbors(model, eng.snapshot(), q)
            assert got_i[0].tolist() == [2, 3, 4]
        finally:
            eng.close()

    def test_regressor_merge_votes_with_delta_targets(self, rng, tmp_path):
        """A delta neighbor contributes its OWN target, not a clamped
        base row's (the predict_from_view gather)."""
        from knn_tpu.mutable.state import predict_from_view

        train, _ = _problem(rng)
        reg_train = Dataset(
            train.features, train.labels,
            raw_targets=np.linspace(0, 1, 200).astype(np.float32))
        model = KNNRegressor(k=2, engine="xla").fit(reg_train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            q = np.full((1, 5), 77.0, np.float32)  # far from the grid
            eng.apply_insert(np.full((2, 5), 77.0, np.float32),
                             [5.0, 7.0], 0)
            view = eng.snapshot()
            d, i = merged_oracle_kneighbors(model, view, q)
            assert sorted(i[0].tolist()) == [200, 201]
            pred = predict_from_view(model, view, d, i)
            np.testing.assert_allclose(pred, [6.0], atol=1e-6)
        finally:
            eng.close()

    def test_validate_insert_typed_errors(self, rng):
        train, _ = _problem(rng)
        clf = KNNClassifier(k=3).fit(train)
        with pytest.raises(ValueError, match=r"\[m, 5\]"):
            validate_insert(clf, [[1.0, 2.0]], [1])
        with pytest.raises(ValueError, match="empty insert"):
            validate_insert(clf, np.empty((0, 5), np.float32), [])
        with pytest.raises(ValueError, match="one label per row"):
            validate_insert(clf, np.ones((2, 5), np.float32), [1])
        with pytest.raises(ValueError, match="integers"):
            validate_insert(clf, np.ones((1, 5), np.float32), [1.5])
        with pytest.raises(ValueError, match="rebuild the index"):
            validate_insert(clf, np.ones((1, 5), np.float32), [99])
        reg = KNNRegressor(k=3).fit(
            Dataset(train.features, train.labels,
                    raw_targets=np.zeros(200, np.float32)))
        with pytest.raises(ValueError, match="finite"):
            validate_insert(reg, np.ones((1, 5), np.float32), [np.nan])


class TestEngine:
    def test_snapshot_is_immutable_under_growth(self, rng, tmp_path):
        """A held view keeps reading its frozen prefix even after enough
        inserts to trigger amortized-doubling reallocation."""
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path), delta_cap=512)
        try:
            eng.apply_insert(np.full((2, 5), 1.0, np.float32), [0, 1], 0)
            view = eng.snapshot()
            frozen = view.features[:view.count].copy()
            eng.apply_insert(
                rng.integers(0, 4, (200, 5)).astype(np.float32),
                rng.integers(0, 4, 200), 0)  # forces 64 -> 256 growth
            assert view.count == 2
            np.testing.assert_array_equal(view.features[:2], frozen)
            assert eng.snapshot().count == 202
        finally:
            eng.close()

    def test_delta_cap_is_backpressure(self, rng, tmp_path):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path), delta_cap=3)
        try:
            eng.apply_insert(np.ones((3, 5), np.float32), [0, 1, 2], 0)
            with pytest.raises(OverloadError, match="delta tier full"):
                eng.apply_insert(np.ones((1, 5), np.float32), [0], 0)
            # Admission-side pre-check: a full tier refuses at
            # submit_mutation, before the queue round-trip.
            b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                             mutable=eng)
            try:
                with pytest.raises(OverloadError, match="delta tier full"):
                    b.submit_mutation(
                        "insert", {"rows": np.ones((1, 5), np.float32),
                                   "values": [0]})
                assert not b._mutations  # never enqueued
            finally:
                b.close()
            # The refusal is not durable: a reboot replays 3 rows, not 4.
            eng.close()
            eng2 = _engine(model, _root(model, tmp_path), delta_cap=3)
            assert eng2.snapshot().count == 3
            eng2.close()
        finally:
            eng.close()

    def test_delete_conflicts_are_typed(self, rng, tmp_path):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            with pytest.raises(MutationConflict, match="no such row"):
                eng.apply_delete([9999], 0)
            with pytest.raises(MutationConflict, match="duplicate id"):
                eng.apply_delete([5, 5], 0)
            eng.apply_delete([5], 0)
            with pytest.raises(MutationConflict, match="already deleted"):
                eng.apply_delete([5], 0)
            with pytest.raises(ValueError, match="empty delete"):
                eng.apply_delete([], 0)
        finally:
            eng.close()

    def test_k_floor_refusal_leaves_wal_untouched(self, rng, tmp_path):
        """A delete that would leave < k live rows is refused BEFORE the
        WAL append — replay must not re-apply a never-acked mutation."""
        train = Dataset(np.eye(4, dtype=np.float32)[:, :3].copy(),
                        np.zeros(4, np.int32))
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            with pytest.raises(MutationConflict, match="below k"):
                eng.apply_delete([0, 1], 0)
            eng.apply_delete([0], 0)  # leaves exactly k=3
            eng.close()
            eng2 = _engine(model, _root(model, tmp_path))
            view = eng2.snapshot()
            assert view.tomb_pos == frozenset({0})
            eng2.close()
        finally:
            eng.close()

    def test_replay_rebuilds_identical_state(self, rng, tmp_path):
        """SIGKILL semantics: a fresh engine over the same directory
        replays the epoch log to the identical view, and continues the
        stable-id sequence (no id reuse)."""
        train, test_x = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        eng = _engine(model, _root(model, tmp_path))
        rows = rng.integers(0, 4, (5, 5)).astype(np.float32)
        eng.apply_insert(rows, [0, 1, 2, 3, 0], 0)
        eng.apply_delete([201, 17], 0)
        before = eng.snapshot()
        d0, i0 = merged_oracle_kneighbors(model, before, test_x)
        eng.close()  # the process "dies"; the WAL is the truth

        eng2 = _engine(model, _root(model, tmp_path))
        try:
            after = eng2.snapshot()
            assert after.seq == before.seq
            assert after.count == before.count
            assert after.tomb_pos == before.tomb_pos
            np.testing.assert_array_equal(
                after.features[:after.count], before.features[:before.count])
            d1, i1 = merged_oracle_kneighbors(model, after, test_x)
            np.testing.assert_array_equal(i0, i1)
            ack = eng2.apply_insert(rows[:1], [1], 0)
            assert ack["seq"] == before.seq + 1
            assert eng2.snapshot().stable[after.count] == 205
        finally:
            eng2.close()

    def test_torn_final_record_dropped_with_warning(self, rng, tmp_path,
                                                    capsys):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path))
        eng.apply_insert(np.ones((1, 5), np.float32), [1], 0)
        eng.close()
        log = artifact.epoch_path(tmp_path / "idx", 1)
        with open(log, "a") as f:
            f.write('{"seq": 2, "op": "insert", "ro')  # crash mid-append
        eng2 = _engine(model, _root(model, tmp_path))
        try:
            assert eng2.snapshot().seq == 1
            assert eng2.snapshot().count == 1
            assert "torn final record" in capsys.readouterr().out
        finally:
            eng2.close()
        # The replay REPAIRED the log: epoch-1 is no longer the last file
        # (boot 2 opened epoch-2) so it gets no torn-tolerance — without
        # the repair, boot 3 would refuse a state boot 2 accepted.
        assert '"seq": 2' not in log.read_text()
        assert artifact.read_epoch_records(log) == ([json.loads(
            log.read_text().splitlines()[0])], False)
        eng3 = _engine(model, _root(model, tmp_path))
        try:
            assert eng3.snapshot().seq == 1
            assert eng3.snapshot().count == 1
        finally:
            eng3.close()

    def test_corrupt_mid_log_is_typed(self, rng, tmp_path):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path))
        eng.apply_insert(np.ones((1, 5), np.float32), [1], 0)
        eng.close()
        log = artifact.epoch_path(tmp_path / "idx", 1)
        good = log.read_text()
        log.write_text("GARBAGE\n" + good)
        with pytest.raises(DataError, match="corrupt epoch-log record"):
            _engine(model, _root(model, tmp_path))

    def test_non_monotonic_seq_is_typed(self, rng, tmp_path):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path))
        eng.apply_insert(np.ones((1, 5), np.float32), [1], 0)
        eng.close()
        log = artifact.epoch_path(tmp_path / "idx", 1)
        log.write_text(log.read_text() * 2)  # seq 1 twice
        with pytest.raises(DataError, match="not seq-monotonic"):
            _engine(model, _root(model, tmp_path))

    def test_freshness_and_export_fields(self, rng, tmp_path, obs_on):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            import time

            eng.apply_insert(np.ones((2, 5), np.float32), [0, 1],
                             time.monotonic_ns())
            eng.apply_delete([0], time.monotonic_ns())
            doc = eng.export()
            assert doc["delta_rows"] == 2 and doc["delta_slots"] == 2
            assert doc["tombstones"] == 1 and doc["seq"] == 2
            assert doc["freshness"]["count"] == 2
            assert doc["freshness"]["p99_ms"] is not None
            names = {i.name for i in obs_on.instruments()}
            assert {"knn_mutable_delta_rows", "knn_mutable_tombstones",
                    "knn_mutable_freshness_ms",
                    "knn_mutable_mutations_total"} <= names
        finally:
            eng.close()


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


class TestCompaction:
    def _artifact_engine(self, rng, tmp_path, *, ivf_cells=None, k=3):
        train, test_x = _problem(rng)
        model = KNNClassifier(k=k, engine="xla").fit(train)
        ivf = None
        if ivf_cells:
            from knn_tpu.index.ivf import IVFIndex

            ivf = IVFIndex.build(train.features, ivf_cells, seed=0)
            model.ivf_ = ivf
        root = save_index(model, tmp_path / "idx", ivf=ivf)
        model = load_index(root)
        return model, test_x, root

    def _compactor(self, eng, holder, **kw):
        def swap(new_model, version, rebase_hook):
            prev = holder.get("version")
            rebase_hook()
            holder["model"], holder["version"] = new_model, version
            return prev

        kw.setdefault("threshold", 10_000)
        kw.setdefault("interval_s", 0.0)
        return Compactor(eng, swap=swap,
                         warm=lambda m: holder.setdefault("warmed", []).
                         append(m), **kw)

    def test_fold_keeps_survivor_order(self, rng):
        """Base survivors in position order, then live delta rows in
        insert order — the deterministic positional space the soak's
        replay reproduces."""
        train, _ = _problem(rng, n=6, q=4)
        base_stable = np.arange(6, dtype=np.int64)
        fold_input = {
            "count": 3,
            "stable": np.array([6, 7, 8], np.int64),
            "features": rng.integers(0, 4, (3, 5)).astype(np.float32),
            "values": np.array([1, 2, 3], np.float32),
            "tomb_stable": frozenset({1, 4, 7}),
            "seq": 5, "generation": 0,
        }
        new_train, new_stable, stats = fold(train, fold_input, base_stable)
        assert new_stable.tolist() == [0, 2, 3, 5, 6, 8]
        np.testing.assert_array_equal(new_train.features[:4],
                                      train.features[[0, 2, 3, 5]])
        np.testing.assert_array_equal(
            new_train.features[4:], fold_input["features"][[0, 2]])
        assert stats == {"base_kept": 4, "base_dropped": 2,
                         "delta_folded": 2, "delta_dropped": 1, "rows": 6}

    def test_compaction_round_trip_preserves_answers(self, rng, tmp_path):
        """Fold + swap + rebase: merged answers (distances) are identical
        before and after, the pointer commits, folded epochs are cleaned,
        and a rebooted engine resumes from the new generation."""
        model, test_x, root = self._artifact_engine(rng, tmp_path)
        eng = _engine(model, root, base_dir=root)
        holder = {"model": model, "version": "v0"}
        comp = self._compactor(eng, holder)
        rows = rng.integers(0, 4, (4, 5)).astype(np.float32)
        eng.apply_insert(rows, [0, 1, 2, 3], 0)
        eng.apply_delete([7, 203], 0)
        before_d, _ = merged_oracle_kneighbors(model, eng.snapshot(),
                                               test_x)
        res = comp.run_once(force=True)
        assert res["compacted"] and res["generation"] == 1
        assert res["rows"] == 200 + 3 - 1
        new_model = holder["model"]
        after_d, after_i = merged_oracle_kneighbors(
            new_model, eng.snapshot(), test_x)
        np.testing.assert_array_equal(after_d, before_d)
        assert eng.snapshot().count == 0  # everything folded
        cur = artifact.read_current(root)
        assert cur["generation"] == 1
        assert artifact.list_epochs(root)[0][0] == 2  # epoch 1 cleaned
        eng.close()

        # Reboot: CURRENT points at gen-1; nothing left to replay.
        base_dir, cur = artifact.resolve_mutable_base(root)
        model2 = load_index(base_dir)
        eng2 = _engine(model2, root, current=cur, base_dir=base_dir)
        try:
            d2, i2 = merged_oracle_kneighbors(model2, eng2.snapshot(),
                                              test_x)
            np.testing.assert_array_equal(d2, after_d)
            np.testing.assert_array_equal(i2, after_i)
        finally:
            eng2.close()

    def test_mid_compaction_writes_survive(self, rng, tmp_path):
        """Writes landing between seal and swap re-anchor onto the new
        generation — zero acknowledged writes lost."""
        model, test_x, root = self._artifact_engine(rng, tmp_path)
        eng = _engine(model, root, base_dir=root)
        holder = {"model": model, "version": "v0"}
        late_row = np.full((1, 5), 9.0, np.float32)

        def swap(new_model, version, rebase_hook):
            # The race: a write is acknowledged AFTER the seal, BEFORE
            # the swap (it landed in the fresh epoch the seal opened).
            eng.apply_insert(late_row, [2], 0)
            rebase_hook()
            holder["model"], holder["version"] = new_model, version
            return "v0"

        comp = Compactor(eng, swap=swap, warm=lambda m: None,
                         threshold=10_000, interval_s=0.0)
        eng.apply_insert(rng.integers(0, 4, (2, 5)).astype(np.float32),
                         [0, 1], 0)
        comp.run_once(force=True)
        try:
            view = eng.snapshot()
            assert view.count == 1  # the late write lives in the new delta
            np.testing.assert_array_equal(view.features[0], late_row[0])
            d, i = merged_oracle_kneighbors(
                holder["model"], view, np.full((1, 5), 9.0, np.float32))
            assert i[0, 0] == 202 and d[0, 0] == 0.0
            eng.close()
            # And it is durable: reboot from the committed pointer.
            base_dir, cur = artifact.resolve_mutable_base(root)
            model2 = load_index(base_dir)
            eng2 = _engine(model2, root, current=cur, base_dir=base_dir)
            assert eng2.snapshot().count == 1
            eng2.close()
        finally:
            eng.close()

    def test_failed_compaction_rolls_back(self, rng, tmp_path, obs_on):
        """A failure before the commit leaves the old generation serving,
        the sealed epoch's records on disk, and the engine answering with
        every acknowledged mutation."""
        model, test_x, root = self._artifact_engine(rng, tmp_path)
        eng = _engine(model, root, base_dir=root)
        holder = {"model": model, "version": "v0"}

        def bad_swap(new_model, version, rebase_hook):
            raise RuntimeError("synthetic swap failure")

        comp = Compactor(eng, swap=bad_swap, warm=lambda m: None,
                         threshold=10_000, interval_s=0.0)
        eng.apply_insert(np.full((1, 5), 9.0, np.float32), [2], 0)
        before = merged_oracle_kneighbors(model, eng.snapshot(), test_x)
        with pytest.raises(RuntimeError, match="synthetic swap failure"):
            comp.run_once(force=True)
        try:
            assert artifact.read_current(root) is None  # never committed
            after = merged_oracle_kneighbors(model, eng.snapshot(), test_x)
            np.testing.assert_array_equal(after[0], before[0])
            assert eng._last_compaction["outcome"] == "rolled_back"
            eng.close()
            eng2 = _engine(model, root, base_dir=root)
            assert eng2.snapshot().count == 1  # the write survived
            eng2.close()
        finally:
            eng.close()

    def test_ivf_partition_reassigned(self, rng, tmp_path):
        """Compacting a partitioned index re-runs cell assignment over
        the folded rows (same seed — deterministic) and persists it."""
        model, test_x, root = self._artifact_engine(rng, tmp_path,
                                                    ivf_cells=8)
        eng = _engine(model, root, base_dir=root)
        holder = {"model": model, "version": "v0"}
        comp = self._compactor(eng, holder)
        try:
            eng.apply_insert(rng.integers(0, 4, (5, 5)).astype(np.float32),
                             [0, 1, 2, 3, 0], 0)
            res = comp.run_once(force=True)
            new_model = holder["model"]
            new_ivf = getattr(new_model, "ivf_", None)
            assert new_ivf is not None and new_ivf.num_cells == 8
            gen_model = load_index(
                artifact.generation_path(root, res["generation"]))
            assert getattr(gen_model, "ivf_", None) is not None
            assert gen_model.train_.num_instances == 205
        finally:
            eng.close()

    def test_one_compaction_at_a_time(self, rng, tmp_path):
        model, _, root = self._artifact_engine(rng, tmp_path)
        eng = _engine(model, root, base_dir=root)
        comp = self._compactor(eng, {"model": model})
        try:
            eng.apply_insert(np.ones((1, 5), np.float32), [1], 0)
            assert comp._lock.acquire(blocking=False)
            try:
                with pytest.raises(CompactionInProgress):
                    comp.run_once(force=True)
            finally:
                comp._lock.release()
        finally:
            eng.close()

    def test_nothing_to_fold_is_a_no_op(self, rng, tmp_path):
        model, _, root = self._artifact_engine(rng, tmp_path)
        eng = _engine(model, root, base_dir=root)
        comp = self._compactor(eng, {"model": model})
        try:
            res = comp.run_once(force=True)
            assert res == {"compacted": False, "reason": "nothing to fold"}
            assert artifact.read_current(root) is None
        finally:
            eng.close()

    def test_version_precondition_checked_at_apply_not_admission(
            self, rng, tmp_path):
        """The delete version precondition is enforced by the ENGINE under
        its own lock (the one the compaction rebase holds) — so a
        precondition naming the pre-compaction version fails AFTER the
        swap, where a handler-side check-then-enqueue would have raced."""
        model, _, root = self._artifact_engine(rng, tmp_path)
        eng = _engine(model, root, base_dir=root, version="v0")
        holder = {"model": model, "version": "v0"}
        comp = self._compactor(eng, holder)
        try:
            eng.apply_insert(np.ones((1, 5), np.float32), [1], 0)
            eng.apply_delete([3], 0, expect_version="v0")  # match: ok
            with pytest.raises(MutationConflict,
                               match="precondition failed"):
                eng.apply_delete([4], 0, expect_version="stale")
            res = comp.run_once(force=True)
            # The rebase moved the engine's version: the old tag now
            # fails, the new one passes.
            with pytest.raises(MutationConflict,
                               match="precondition failed"):
                eng.apply_delete([5], 0, expect_version="v0")
            eng.apply_delete([5], 0,
                             expect_version=res["index_version"])
        finally:
            eng.close()

    def test_failed_rebase_restores_old_model_and_engine(
            self, rng, tmp_path):
        """A rebase that raises must leave BOTH halves of the pairing
        untouched: swap_model restores the old (model, version), and the
        engine — which validates before its first assignment — still
        answers with the old generation's state."""
        train, _ = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        root = _root(model, tmp_path)
        eng = _engine(model, root, version="v0")
        b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                         index_version="v0", mutable=eng)
        try:
            eng.apply_insert(np.ones((2, 5), np.float32), [1, 0], 0)
            before = eng.snapshot()
            fold_input = eng.seal()
            model2 = KNNClassifier(k=3, engine="xla").fit(train)
            bad_stable = np.array([5, 3, 1], np.int64)  # not ascending
            with pytest.raises(DataError, match="not strictly ascending"):
                b.swap_model(model2, "v1",
                             hook=lambda: eng.rebase(fold_input, model2,
                                                     bad_stable, 1,
                                                     version="v1"))
            assert b._model is model and b._index_version == "v0"
            after = eng.snapshot()
            assert after.count == before.count
            assert after.base_n == before.base_n
            assert after.generation == before.generation
            # The old version still satisfies the precondition — the
            # engine never moved to "v1".
            eng.apply_delete([0], 0, expect_version="v0")
        finally:
            b.close()
            eng.close()

    def test_ack_version_is_stamped_under_the_engine_lock(
            self, rng, tmp_path):
        """A mutation ack's index_version comes from the ENGINE (same
        lock the rebase holds), so the ack's positional ids and its
        version tag always name one generation — a post-apply read of
        the batcher's tag could pair old-space ids with the new tag and
        let a delete precondition pass against the wrong rows."""
        model, _, root = self._artifact_engine(rng, tmp_path)
        eng = _engine(model, root, base_dir=root, version="v0")
        holder = {"model": model, "version": "v0"}
        comp = self._compactor(eng, holder)
        try:
            ack = eng.apply_insert(np.ones((1, 5), np.float32), [1], 0)
            assert ack["index_version"] == "v0"
            res = comp.run_once(force=True)
            ack2 = eng.apply_delete([3], 0)
            assert ack2["index_version"] == res["index_version"] != "v0"
        finally:
            eng.close()

    def test_leftover_repair_tmp_file_does_not_brick_boot(
            self, rng, tmp_path):
        """A crash inside repair_epoch's write-then-replace window leaves
        epoch-N.jsonl.tmp behind; list_epochs must skip it (the original
        epoch is intact) instead of refusing to boot the artifact."""
        model, _, root = self._artifact_engine(rng, tmp_path)
        eng = _engine(model, root, base_dir=root)
        eng.apply_insert(np.ones((1, 5), np.float32), [1], 0)
        eng.close()
        stale = artifact.epoch_path(root, 1).with_name(
            "epoch-00000001.jsonl.tmp")
        stale.write_text('{"seq": 1, "op": "ins')  # torn repair attempt
        assert [n for n, _ in artifact.list_epochs(root)] == [1]
        eng2 = _engine(model, root, base_dir=root)
        try:
            assert eng2.snapshot().count == 1
        finally:
            eng2.close()

    def test_post_swap_commit_failure_is_not_reported_as_rollback(
            self, rng, tmp_path, monkeypatch):
        """A failure AFTER the swap (CURRENT.json commit) means the NEW
        generation is serving — the outcome must say commit_failed, never
        rolled_back (an operator acting on 'rolled_back' would reason
        about the wrong generation)."""
        from knn_tpu.mutable.compact import CompactionCommitFailed

        model, _, root = self._artifact_engine(rng, tmp_path)
        eng = _engine(model, root, base_dir=root)
        holder = {"model": model, "version": "v0"}
        comp = self._compactor(eng, holder)
        monkeypatch.setattr(
            artifact, "write_current",
            lambda *a, **k: (_ for _ in ()).throw(OSError("disk full")))
        try:
            eng.apply_insert(np.ones((1, 5), np.float32), [1], 0)
            with pytest.raises(CompactionCommitFailed,
                               match="pointer commit failed"):
                comp.run_once(force=True)
            assert eng._last_compaction["outcome"] == "commit_failed"
            assert holder["version"] != "v0"  # the swap DID happen
            # Reboot-safety: no pointer committed, the sealed epoch is
            # still on disk — the old base + full replay reconstruct
            # every acknowledged write.
            assert artifact.read_current(root) is None
            eng.close()
            eng2 = _engine(model, root, base_dir=root)
            assert eng2.snapshot().count == 1
            eng2.close()
        finally:
            eng.close()

    def test_fold_promotes_fractional_regression_targets(self, rng):
        """A sketch-less regressor base stores targets as int labels
        (Dataset.targets falls back); folding fractional acked targets
        through that dtype would silently change answers — fold must
        promote to raw_targets instead."""
        train = Dataset(rng.integers(0, 4, (6, 5)).astype(np.float32),
                        rng.integers(0, 3, 6).astype(np.int32))
        assert train.raw_targets is None
        fold_input = {
            "count": 2,
            "stable": np.array([6, 7], np.int64),
            "features": rng.integers(0, 4, (2, 5)).astype(np.float32),
            "values": np.array([2.7, -3.25], np.float32),
            "tomb_stable": frozenset(),
            "seq": 2, "generation": 0,
        }
        new_train, _, _ = fold(train, fold_input,
                               np.arange(6, dtype=np.int64))
        np.testing.assert_array_equal(
            new_train.targets[6:], np.array([2.7, -3.25], np.float32))
        np.testing.assert_array_equal(new_train.targets[:6],
                                      train.targets)

    def test_threshold_kick_compacts_without_interval_thread(
            self, rng, tmp_path):
        """interval_s == 0 (zero-thread mode): crossing the threshold
        must still compact — the CLI help promises threshold kicks work
        without the timer thread."""
        import time as _time

        model, _, root = self._artifact_engine(rng, tmp_path)
        eng = _engine(model, root, base_dir=root)
        holder = {"model": model, "version": "v0"}
        comp = self._compactor(eng, holder, threshold=2, interval_s=0.0)
        comp.start()  # no-op at interval 0: no thread to consume kicks
        assert comp._thread is None
        try:
            eng.apply_insert(np.ones((2, 5), np.float32), [1, 0], 0)
            deadline = _time.monotonic() + 30
            while comp.compactions == 0 and _time.monotonic() < deadline:
                _time.sleep(0.05)
            assert comp.compactions == 1
            assert holder["version"] != "v0"
            assert eng.snapshot().count == 0  # folded
        finally:
            comp.stop()
            eng.close()


class TestEmptyViewBitIdentity:
    """Acceptance pin: mutable-on serving with an empty delta/tombstone
    set is byte-identical to mutable-off on EVERY rung."""

    def test_empty_view_skips_the_merge_wrapper(self, rng):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0)
        try:
            empty = MutableView(
                features=np.zeros((0, 5), np.float32),
                values=np.zeros(0, np.float32),
                stable=np.zeros(0, np.int64), count=0,
                tomb_pos=frozenset(), tomb_base=np.empty(0, np.int64),
                tomb_delta_slots=np.empty(0, np.int64), seq=0,
                base_n=200, generation=0)
            plain = b._rungs(model)
            viewed = b._rungs(model, empty)
            assert [n for n, _ in plain] == [n for n, _ in viewed]
            # The closures are the plain rungs, never the merge wrapper.
            for name, fn in viewed:
                assert "_merged_rung" not in fn.__qualname__, name
        finally:
            b.close()

    def test_every_rung_bit_identical_with_empty_view(self, rng, tmp_path):
        """Every ladder rung (ivf, fast, xla, oracle) answers the same
        bytes through a mutable-on batcher with no mutations as through
        a mutable-off one."""
        from knn_tpu.index.ivf import IVFIndex, IVFServing

        train, test_x = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        model.ivf_ = IVFIndex.build(train.features, 8, seed=0)
        root = save_index(model, tmp_path / "idx", ivf=model.ivf_)
        model = load_index(root)
        eng = _engine(model, root, base_dir=root)
        ivf_serving = IVFServing(4, 8)
        b_off = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                             ivf=ivf_serving)
        b_on = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                            ivf=ivf_serving, mutable=eng)
        try:
            view = eng.snapshot()
            assert view.empty
            rungs_off = b_off._rungs(model)
            rungs_on = b_on._rungs(model, view)
            assert [n for n, _ in rungs_off] == [n for n, _ in rungs_on]
            assert "ivf" in [n for n, _ in rungs_on]
            for (name, f_off), (_, f_on) in zip(rungs_off, rungs_on):
                d0, i0 = f_off(test_x)
                d1, i1 = f_on(test_x)
                assert np.asarray(d0).tobytes() == \
                    np.asarray(d1).tobytes(), name
                assert np.asarray(i0).tobytes() == \
                    np.asarray(i1).tobytes(), name
        finally:
            b_off.close()
            b_on.close()
            eng.close()

    def test_served_bytes_identical_end_to_end(self, rng, tmp_path):
        """Whole-stack: submit through both batchers, compare the served
        (dists, idx, preds) byte-for-byte."""
        train, test_x = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        root = save_index(model, tmp_path / "idx")
        model = load_index(root)
        eng = _engine(model, root, base_dir=root)
        b_off = MicroBatcher(model, max_batch=8, max_wait_ms=0.0)
        b_on = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                            mutable=eng)
        try:
            d0, i0 = b_off.submit(test_x, "kneighbors").result(60)
            d1, i1 = b_on.submit(test_x, "kneighbors").result(60)
            assert d0.tobytes() == d1.tobytes()
            assert i0.tobytes() == i1.tobytes()
            p0 = b_off.submit(test_x, "predict").result(60)
            p1 = b_on.submit(test_x, "predict").result(60)
            assert np.asarray(p0).tobytes() == np.asarray(p1).tobytes()
        finally:
            b_off.close()
            b_on.close()
            eng.close()


class TestShadowScoringLiveView:
    def test_stale_answer_burns_recall(self, rng, tmp_path):
        """A served answer that IGNORED the delta tier (staleness) must
        score recall < 1 against the live view; the honest merged answer
        scores exactly 1."""
        from knn_tpu.obs.quality import ShadowScorer

        train, _ = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            q = np.full((2, 5), 50.0, np.float32)  # far from the grid
            # k=3 delta rows AT the query: the live top-k is delta-only,
            # so a base-only (stale) answer scores recall 0 even with the
            # grid's distance ties among base rows.
            eng.apply_insert(np.full((3, 5), 50.0, np.float32),
                             [1, 1, 1], 0)
            view = eng.snapshot()
            stale_d, stale_i = model.kneighbors(
                Dataset(q, np.zeros(2, np.int32)))  # base-only: stale
            fresh_d, fresh_i = merged_oracle_kneighbors(model, view, q)
            sc = ShadowScorer(1.0, queue_cap=8)
            for d, i in ((stale_d, stale_i), (fresh_d, fresh_i)):
                sc.offer(features=q, kind="kneighbors", dists=d, idx=i,
                         preds=None, rung="fast", model=model,
                         version="v", mview=view)
            assert sc.drain(30)
            sc.close()
            stats = sc.export()["rungs"]["fast"]
            assert stats["scored"] == 2
            # stale scored < 1, fresh scored 1 -> mean strictly between.
            assert 0.0 < stats["recall"] < 1.0
        finally:
            eng.close()

    def test_fresh_answer_scores_exactly_one(self, rng, tmp_path):
        from knn_tpu.obs.quality import ShadowScorer

        train, test_x = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            eng.apply_insert(rng.integers(0, 4, (3, 5)).astype(np.float32),
                             [0, 1, 2], 0)
            eng.apply_delete([0], 0)
            view = eng.snapshot()
            d, i = merged_oracle_kneighbors(model, view, test_x)
            sc = ShadowScorer(1.0, queue_cap=8)
            sc.offer(features=test_x, kind="kneighbors", dists=d, idx=i,
                     preds=None, rung="oracle", model=model, version="v",
                     mview=view)
            assert sc.drain(30)
            sc.close()
            stats = sc.export()["rungs"]["oracle"]
            assert stats["recall"] == 1.0
            assert stats["divergence"] == {}
        finally:
            eng.close()


class TestMutableHTTP:
    @pytest.fixture
    def served_mutable(self, rng, obs_on, tmp_path):
        from knn_tpu.serve.server import ServeApp, make_server

        train, test_x = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        root = save_index(model, tmp_path / "idx")
        model = load_index(root)
        app = ServeApp(model, max_batch=16, max_wait_ms=1.0,
                       index_path=str(root), index_version="v0",
                       mutable=True, delta_cap=8,
                       compact_threshold=10_000, compact_interval_s=0.0)
        server = make_server(app)
        host, port = server.server_address[:2]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        app.warm((1, 4))
        try:
            yield f"http://{host}:{port}", model, test_x, app
        finally:
            server.shutdown()
            server.server_close()
            app.close()
            thread.join(timeout=10)

    def _post(self, base, path, payload=None):
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            base + path,
            data=json.dumps(payload if payload is not None else {}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def _get(self, base, path):
        import urllib.error
        import urllib.request

        try:
            with urllib.request.urlopen(base + path, timeout=30) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError as e:
            return e.code, e.read().decode()

    def test_read_after_write_with_sequence_point(self, served_mutable):
        base, model, test_x, app = served_mutable
        rows = np.full((3, 5), 9.0, np.float32)  # k rows AT the query
        st, ack = self._post(base, "/insert",
                             {"rows": rows.tolist(), "labels": [2, 2, 2]})
        assert st == 200 and ack["ids"] == [200, 201, 202]
        assert ack["seq"] == 1
        st, body = self._post(base, "/kneighbors",
                              {"instances": rows[:1].tolist()})
        assert st == 200
        assert body["mutation_seq"] >= 1
        assert body["indices"][0] == [200, 201, 202]
        assert body["distances"][0] == [0.0, 0.0, 0.0]
        st, body = self._post(base, "/predict",
                              {"instances": rows[:1].tolist()})
        assert st == 200 and body["predictions"] == [2]

    def test_typed_status_matrix(self, served_mutable):
        base, model, test_x, app = served_mutable
        row = test_x[0].tolist()
        assert self._post(base, "/insert", {"rows": [[1.0]],
                                            "labels": [0]})[0] == 400
        assert self._post(base, "/insert", {"rows": [row]})[0] == 400
        assert self._post(base, "/insert", {"rows": [row],
                                            "labels": [99]})[0] == 400
        assert self._post(base, "/delete", {"ids": [99999]})[0] == 409
        assert self._post(base, "/delete", {})[0] == 400
        st, body = self._post(base, "/delete",
                              {"ids": [0], "index_version": "stale"})
        assert st == 409 and "precondition" in body["error"]
        for _ in range(8):  # fill the delta tier (cap 8)
            self._post(base, "/insert", {"rows": [row], "labels": [1]})
        st, body = self._post(base, "/insert",
                              {"rows": [row], "labels": [1]})
        assert st == 429 and "delta tier full" in body["error"]

    def test_compact_swaps_version_and_preserves_answers(
            self, served_mutable):
        base, model, test_x, app = served_mutable
        self._post(base, "/insert",
                   {"rows": np.full((2, 5), 9.0).tolist(),
                    "labels": [1, 2]})
        self._post(base, "/delete", {"ids": [5]})
        st, before = self._post(base, "/kneighbors",
                                {"instances": test_x[:4].tolist()})
        st, res = self._post(base, "/admin/compact")
        assert st == 200 and res["compacted"]
        assert res["index_version"] != "v0"
        assert res["previous_version"] == "v0"
        assert app.index_version == res["index_version"]
        st, after = self._post(base, "/kneighbors",
                               {"instances": test_x[:4].tolist()})
        assert st == 200
        assert after["distances"] == before["distances"]
        assert after["index_version"] == res["index_version"]
        # Idempotent trigger with nothing pending.
        st, res2 = self._post(base, "/admin/compact")
        assert st == 200 and res2["compacted"] is False

    def test_hot_reload_disabled_under_mutable(self, served_mutable):
        base, *_ = served_mutable
        st, body = self._post(base, "/admin/reload", {})
        assert st == 400
        assert "compact" in body["error"]

    def test_observability_surfaces(self, served_mutable):
        base, model, test_x, app = served_mutable
        self._post(base, "/insert",
                   {"rows": [test_x[0].tolist()], "labels": [1]})
        st, body = self._get(base, "/healthz")
        blk = json.loads(body)["mutable"]
        assert blk["delta_rows"] == 1 and blk["epoch"] == 1
        assert blk["freshness"]["count"] == 1
        st, text = self._get(base, "/metrics")
        for row in ("knn_mutable_delta_rows 1", "knn_mutable_tombstones 0",
                    "knn_mutable_freshness_ms", "knn_mutable_epoch"):
            assert row in text, row
        st, body = self._get(base, "/debug/capacity")
        assert json.loads(body)["mutable"]["delta_rows"] == 1

    def test_draining_refuses_mutations_503(self, served_mutable):
        base, model, test_x, app = served_mutable
        app.draining = True
        app.batcher.begin_drain()
        st, body = self._post(base, "/insert",
                              {"rows": [test_x[0].tolist()],
                               "labels": [1]})
        assert st == 503 and "draining" in body["error"]


class TestWALReplicationEdges:
    """The two WAL edge cases primary-failover catch-up depends on
    (docs/SERVING.md §Running a replica set): a seq GAP in a replayed
    epoch stream is a typed refusal (acked records vanished — replaying
    past the hole would serve a history that never happened), and
    re-applying an ALREADY-applied seq is an idempotent no-op (the
    shipper re-sends from a conservative cursor after a resync)."""

    def test_boot_replay_seq_gap_is_typed_never_skipped(self, rng,
                                                        tmp_path):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path))
        for v in (0, 1, 2):
            eng.apply_insert(np.full((1, 5), float(v), np.float32),
                             [v], 0)
        eng.close()
        # Surgically drop the MIDDLE record: an acknowledged write
        # vanished from the stream.
        path = artifact.epoch_path(_root(model, tmp_path), 1)
        lines = [ln for ln in path.read_text().splitlines() if ln]
        assert len(lines) == 3
        path.write_text("\n".join([lines[0], lines[2]]) + "\n")
        with pytest.raises(DataError, match="seq gap"):
            _engine(model, _root(model, tmp_path))

    def test_reapply_already_applied_seq_is_idempotent_noop(
            self, rng, tmp_path):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            eng.apply_insert(np.ones((2, 5), np.float32), [0, 1], 0)
            eng.apply_delete([3], 0)
            records, seq = eng.records_since(0)
            assert [r["seq"] for r in records] == [1, 2]
            assert all("digest" in r for r in records)
            before = eng.snapshot()
            for rec in records:  # the shipper's conservative re-send
                out = eng.apply_replicated(rec)
                assert out == {"applied": False, "seq": seq}
            after = eng.snapshot()
            assert after.seq == before.seq
            assert after.count == before.count
            assert after.tomb_pos == before.tomb_pos
        finally:
            eng.close()
        # ...and the no-op appended NOTHING to the WAL: a reboot replays
        # the identical two records.
        eng2 = _engine(model, _root(model, tmp_path))
        try:
            records2, seq2 = eng2.records_since(0)
            assert seq2 == seq
            assert [(r["seq"], r["op"]) for r in records2] == [
                (1, "insert"), (2, "delete")]
        finally:
            eng2.close()

    def test_reapply_with_divergent_content_is_typed(self, rng,
                                                     tmp_path):
        """Same seq, different digest: the two logs disagree about
        history — silent skip OR silent apply would both be corruption."""
        from knn_tpu.mutable.state import WALDivergence

        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        eng = _engine(model, _root(model, tmp_path))
        try:
            eng.apply_insert(np.ones((1, 5), np.float32), [0], 0)
            records, _seq = eng.records_since(0)
            evil = dict(records[0])
            evil["rows"] = [[9.0, 9.0, 9.0, 9.0, 9.0]]
            with pytest.raises(WALDivergence, match="diverged"):
                eng.apply_replicated(evil)
        finally:
            eng.close()
