"""Request-tracing contract tests (docs/OBSERVABILITY.md §Request tracing).

The load-bearing claims:

- **propagation** — under concurrent mixed predict/kneighbors load, every
  response's request_id maps to exactly ONE flight-recorder timeline whose
  phase durations are all closed and sum to within tolerance of its
  ``request_ms`` — including requests that degraded rungs under fault
  injection and requests that expired mid-flight;
- **the recorder is bounded** — a ring of the last N plus a slowest-K
  reservoir, with a Perfetto export whose B/E events always match;
- **exemplars** — the OpenMetrics exposition links histogram buckets to
  trace ids, while the plain Prometheus exposition stays byte-compatible
  (no exemplar syntax leaks into the 0.0.4 format);
- **SLO burn rates** — the multi-window burn math, its ring rotation, and
  the ``knn_slo_*`` gauge export;
- **the HTTP weave** — ``x-request-id`` echo on every response (errors
  included), malformed ids rejected 400, ``/debug`` endpoints, the
  access log, the ``/healthz`` SLO block.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.obs.metrics import MetricsRegistry
from knn_tpu.obs.reqtrace import (
    MAX_REQUEST_ID_LEN,
    FlightRecorder,
    RequestTrace,
    activate,
    emit,
    gen_request_id,
    valid_request_id,
)
from knn_tpu.obs.slo import SLOTracker, window_label
from knn_tpu.resilience import faults
from knn_tpu.resilience.errors import DeadlineExceededError, OverloadError
from knn_tpu.serve.batcher import MicroBatcher


@pytest.fixture
def obs_on():
    """Enabled + isolated observability for metric assertions."""
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


def _problem(rng, n=300, q=40, d=5, c=5):
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, q // 2, replace=False)],
         rng.integers(0, 4, (q - q // 2, d)).astype(np.float32)]
    )
    return (Dataset(train_x, train_y),
            Dataset(test_x, np.zeros(len(test_x), np.int32)))


# ---------------------------------------------------------------------------
# RequestTrace + FlightRecorder units


class TestRequestIds:
    def test_generated_ids_are_valid_and_distinct(self):
        ids = {gen_request_id() for _ in range(64)}
        assert len(ids) == 64
        assert all(valid_request_id(i) for i in ids)

    @pytest.mark.parametrize("bad", [
        "", "x" * (MAX_REQUEST_ID_LEN + 1), "has space", "tab\tchar",
        "new\nline", "unicode-é", "ctrl\x01",
    ])
    def test_invalid_ids_rejected(self, bad):
        assert not valid_request_id(bad)

    def test_boundary_ok(self):
        assert valid_request_id("x" * MAX_REQUEST_ID_LEN)
        assert valid_request_id("a-b_c.d/e:f")


class TestRequestTrace:
    def test_phases_close_and_sum(self):
        t = RequestTrace("predict", 1)
        t.phase_start("queue_wait")
        t.phase_end("queue_wait")
        t.phase_start("dispatch")
        t.phase_end("dispatch")
        t.finish("ok")
        d = t.to_dict()
        assert [p["phase"] for p in d["phases"]] == ["queue_wait", "dispatch"]
        assert all(p["ms"] is not None for p in d["phases"])
        assert sum(p["ms"] for p in d["phases"]) <= d["request_ms"] + 0.001

    def test_finish_closes_open_phases_and_is_idempotent(self):
        t = RequestTrace("predict", 1)
        t.phase_start("queue_wait")
        t.finish("expired")
        t.finish("ok")  # second outcome must NOT win
        d = t.to_dict()
        assert d["outcome"] == "expired"
        assert d["phases"][0]["ms"] is not None
        first_ms = d["request_ms"]
        t.finish("error")
        assert t.to_dict()["request_ms"] == first_ms

    def test_annotations_visible_after_finish(self):
        rec = FlightRecorder(capacity=2)
        t = rec.new_trace("predict", 1, request_id="late-note")
        t.finish("ok")
        t.annotate(status=200)  # the handler stamps AFTER the worker
        assert rec.find("late-note")["status"] == 200

    def test_to_dict_is_a_snapshot(self):
        t = RequestTrace("predict", 1)
        t.phase_start("queue_wait")
        t.finish("ok")
        d = t.to_dict()
        d["phases"][0]["ms"] = -1
        d["outcome"] = "tampered"
        assert t.to_dict()["phases"][0]["ms"] != -1
        assert t.to_dict()["outcome"] == "ok"


class TestFlightRecorder:
    def test_ring_keeps_newest_n(self):
        rec = FlightRecorder(capacity=4, slowest_k=0)
        for i in range(10):
            rec.new_trace("predict", 1, request_id=f"r{i}").finish("ok")
        recent = rec.recent()
        assert [tl["request_id"] for tl in recent] == ["r9", "r8", "r7", "r6"]
        assert rec.stats()["completed"] == 10
        assert rec.recent(2) == recent[:2]

    def test_slowest_reservoir(self):
        # Drive the reservoir with deterministic walls: finish() computes
        # request_ms from the wall clock, so build finished traces by hand
        # and record() them with explicit latencies.
        rec = FlightRecorder(capacity=2, slowest_k=3)
        for i, ms in enumerate([5.0, 50.0, 1.0, 30.0, 2.0, 40.0]):
            t = RequestTrace("predict", 1, request_id=f"s{i}", recorder=None)
            t.outcome = "ok"
            t.request_ms = ms
            rec.record(t)
        slowest = [tl["request_id"] for tl in rec.slowest()]
        assert slowest == ["s1", "s5", "s3"]  # 50, 40, 30 — slowest first
        # Ring evicted s0..s3, but the reservoir still resolves s1.
        assert rec.find("s1")["request_ms"] == 50.0

    def test_find_missing(self):
        assert FlightRecorder(capacity=2).find("nope") is None

    def test_perfetto_export_balanced(self):
        rec = FlightRecorder(capacity=8)
        for i in range(3):
            t = rec.new_trace("predict", 1, request_id=f"p{i}")
            t.phase_start("queue_wait")
            t.phase_end("queue_wait")
            t.phase_start("dispatch")
            t.attempt("fast", False, 0.5, error="DeviceError")
            t.attempt("xla", True, 0.4)
            t.event("fallback", from_rung="fast", to="xla")
            t.finish("ok")
        doc = rec.to_chrome_trace()
        ev = doc["traceEvents"]
        assert sum(1 for e in ev if e["ph"] == "B") == \
            sum(1 for e in ev if e["ph"] == "E")
        names = {e["name"] for e in ev}
        assert {"queue_wait", "dispatch", "attempt:fast", "attempt:xla",
                "fallback", "thread_name"} <= names
        # One track per request.
        tids = {e["tid"] for e in ev if e["ph"] == "M"}
        assert len(tids) == 3

    def test_bounds_validated(self):
        with pytest.raises(ValueError, match="capacity"):
            FlightRecorder(capacity=0)
        with pytest.raises(ValueError, match="slowest_k"):
            FlightRecorder(capacity=1, slowest_k=-1)


class TestActiveContext:
    def test_emit_is_noop_when_unarmed(self):
        emit("nothing", x=1)  # must not raise, must not allocate traces

    def test_emit_lands_in_all_armed_traces(self):
        a, b = RequestTrace("predict", 1), RequestTrace("kneighbors", 1)
        with activate([a, b]):
            emit("breaker.transition", to_state="open")
        emit("after", x=1)  # disarmed again
        for t in (a, b):
            evs = t.to_dict()["events"]
            assert [e["event"] for e in evs] == ["breaker.transition"]

    def test_in_place_list_update_reflects(self):
        a, b = RequestTrace("predict", 1), RequestTrace("predict", 1)
        armed = [a, b]
        with activate(armed):
            armed[:] = [a]  # b expired mid-fallback
            emit("fallback", to="oracle")
        assert len(a.to_dict()["events"]) == 1
        assert len(b.to_dict()["events"]) == 0

    def test_nesting_restores(self):
        a, b = RequestTrace("predict", 1), RequestTrace("predict", 1)
        with activate([a]):
            with activate([b]):
                emit("inner")
            emit("outer")
        assert [e["event"] for e in a.to_dict()["events"]] == ["outer"]
        assert [e["event"] for e in b.to_dict()["events"]] == ["inner"]


# ---------------------------------------------------------------------------
# Exemplars + OpenMetrics exposition


class TestExemplars:
    def test_last_exemplar_wins_per_bucket(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_ms", buckets=(1.0, 10.0))
        h.observe(0.5, exemplar={"trace_id": "first"})
        h.observe(0.7, exemplar={"trace_id": "second"})
        h.observe(5.0)  # no exemplar: bucket 1 stays empty
        ex = h.exemplars()
        assert ex[0][0] == (("trace_id", "second"),)
        assert ex[1] is None

    def test_openmetrics_format(self):
        reg = MetricsRegistry()
        reg.counter("reqs_total", help="requests").add(3)
        reg.gauge("temp").set(1.5)
        h = reg.histogram("lat_ms", buckets=(1.0,))
        h.observe(0.5, exemplar={"trace_id": "t1"})
        om = reg.to_openmetrics()
        lines = om.splitlines()
        assert lines[-1] == "# EOF"
        # Counter FAMILY drops _total; the sample keeps it.
        assert "# TYPE reqs counter" in lines
        assert any(ln.startswith("reqs_total 3") for ln in lines)
        ex_line = next(ln for ln in lines if "# {" in ln)
        assert ex_line.startswith('lat_ms_bucket{le="1"} 1 # '
                                  '{trace_id="t1"} 0.5 ')

    def test_prometheus_exposition_unchanged(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_ms", buckets=(1.0,))
        h.observe(0.5, exemplar={"trace_id": "t1"})
        prom = reg.to_prometheus()
        assert "# {" not in prom  # exemplar syntax must not leak into 0.0.4
        assert 'lat_ms_bucket{le="1"} 1' in prom

    def test_helper_routes_exemplar(self, obs_on):
        obs.histogram_observe("x_ms", 0.5, buckets=(1.0,),
                              exemplar={"trace_id": "via-helper"})
        assert 'trace_id="via-helper"' in obs_on.to_openmetrics()


# ---------------------------------------------------------------------------
# SLO tracker


class TestSLOTracker:
    def test_burn_math(self):
        s = SLOTracker(availability_target=0.99, latency_target_ms=10.0,
                       latency_target=0.9, fast_rung_target=0.9,
                       windows_s=(60,))
        for _ in range(9):
            s.record(True, 1.0)
        s.record(False, 1.0)  # 10% bad availability, budget 1% -> burn 10
        burns = s.burn_rates()
        assert burns["availability"]["1m"] == pytest.approx(10.0)
        # latency: 9 good of 10 -> 10% bad over a 10% budget -> burn 1.
        assert burns["latency"]["1m"] == pytest.approx(1.0)
        assert burns["fast_rung"]["1m"] == pytest.approx(1.0)

    def test_degraded_spends_fast_rung_budget_only(self):
        s = SLOTracker(fast_rung_target=0.5, windows_s=(60,))
        s.record(True, 1.0, degraded=True)
        s.record(True, 1.0, degraded=False)
        burns = s.burn_rates()
        assert burns["availability"]["1m"] == 0.0
        assert burns["fast_rung"]["1m"] == pytest.approx(1.0)  # 50%/50%

    def test_no_traffic_no_burn(self):
        s = SLOTracker(windows_s=(5,))
        assert s.burn_rates()["availability"]["5s"] == 0.0

    def test_ring_rotation_expires_old_outcomes(self, monkeypatch):
        import knn_tpu.obs.slo as slo_mod

        clock = [1000.0]
        monkeypatch.setattr(slo_mod.time, "monotonic", lambda: clock[0])
        s = SLOTracker(windows_s=(2, 5))
        s.record(False, 1.0)
        assert s.burn_rates()["availability"]["2s"] > 0
        clock[0] += 3  # past the 2 s window, inside the 5 s one
        burns = s.burn_rates()
        assert burns["availability"]["2s"] == 0.0
        assert burns["availability"]["5s"] > 0
        clock[0] += 10  # past both
        assert s.burn_rates()["availability"]["5s"] == 0.0

    def test_slot_reuse_resets_stale_counts(self, monkeypatch):
        import knn_tpu.obs.slo as slo_mod

        clock = [0.0]
        monkeypatch.setattr(slo_mod.time, "monotonic", lambda: clock[0])
        s = SLOTracker(windows_s=(2,))
        s.record(False, 1.0)
        clock[0] += 2  # ring size 2: same slot index, new second
        s.record(True, 1.0)
        total, ok, _, _ = s.window_counts(2)
        assert (total, ok) == (1, 1)  # the stale failure was reset

    def test_long_windows_get_coarse_slots_bounded_ring(self):
        # A 30-day window must not allocate 2.6M per-second slots: the
        # ring is bounded at ~3600 slots via coarser slot widths.
        month = 30 * 24 * 3600
        s = SLOTracker(windows_s=(3600, month))
        assert len(s._ring) <= 3600
        assert s.slot_s == -(-month // 3600)
        s.record(False, 1.0)
        assert s.burn_rates()["availability"]["720h"] > 0
        # Default windows keep per-second resolution.
        assert SLOTracker().slot_s == 1

    def test_validation(self):
        with pytest.raises(ValueError, match="availability_target"):
            SLOTracker(availability_target=1.0)
        with pytest.raises(ValueError, match="latency_target_ms"):
            SLOTracker(latency_target_ms=0)
        with pytest.raises(ValueError, match="windows_s"):
            SLOTracker(windows_s=())

    def test_export_sets_gauges(self, obs_on):
        s = SLOTracker(windows_s=(300, 3600))
        s.record(True, 1.0)
        out = s.export()
        assert out["windows"] == ["5m", "1h"]
        prom = obs_on.to_prometheus()
        assert 'knn_slo_burn_rate{objective="availability",window="5m"}' \
            in prom
        assert 'knn_slo_target{objective="fast_rung"}' in prom

    def test_window_labels(self):
        assert window_label(300) == "5m"
        assert window_label(3600) == "1h"
        assert window_label(5) == "5s"


# ---------------------------------------------------------------------------
# Trace-context propagation through the batcher under concurrent load
# (the satellite: N threads x mixed kinds, every request_id -> exactly one
# timeline whose phases sum to ~request_ms, degraded + expired included)


class TestBatcherTracePropagation:
    TOLERANCE_NOTE = "phases are contiguous: queue_wait + dispatch ~ total"

    def _check_timeline(self, tl):
        assert tl["outcome"] is not None
        open_phases = [p for p in tl["phases"] if p["ms"] is None]
        assert not open_phases, (tl["request_id"], open_phases)
        phase_sum = sum(p["ms"] for p in tl["phases"])
        # Contiguity tolerance: scheduling gaps between enqueue->pickup->
        # terminal are what's NOT covered; they must stay small relative
        # to the request (2 ms absolute floor for coarse CI clocks).
        assert phase_sum <= tl["request_ms"] * 1.05 + 2.0, tl
        if tl["outcome"] == "ok":
            assert tl["rung"] is not None
            assert tl["phases"][-1]["phase"] == "dispatch"

    def test_concurrent_mixed_load_every_id_resolves(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        model.predict(test)  # warm: traced walls exclude compile
        rec = FlightRecorder(capacity=4096, slowest_k=8)
        results = {}
        lock = threading.Lock()

        def client(cid):
            mine = {}
            for i in range(12):
                kind = "predict" if (cid + i) % 2 == 0 else "kneighbors"
                lo = (cid * 12 + i) % (test.num_instances - 3)
                rows = test.features[lo:lo + 1 + (i % 3)]
                trace = rec.new_trace(kind, rows.shape[0])
                try:
                    h = batcher.submit(rows, kind, trace=trace)
                    h.result(timeout=60)
                    mine[trace.request_id] = "ok"
                except Exception as e:  # noqa: BLE001 — recorded
                    mine[trace.request_id] = type(e).__name__
            with lock:
                results.update(mine)

        with MicroBatcher(model, max_batch=8, max_wait_ms=1.0,
                          recorder=rec) as batcher:
            # A short seeded fault burst: early dispatches degrade to the
            # xla... -> oracle rungs, so the propagation proof covers
            # degraded requests, not just clean ones.
            with faults.inject("serve.dispatch=3:device", seed=11):
                threads = [threading.Thread(target=client, args=(c,))
                           for c in range(6)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=120)
        assert len(results) == 6 * 12
        timelines = {tl["request_id"]: tl for tl in rec.recent()}
        assert len(timelines) == len(results), "duplicate or dropped ids"
        degraded = 0
        for rid, outcome in results.items():
            tl = timelines[rid]
            self._check_timeline(tl)
            if outcome == "ok":
                assert tl["outcome"] == "ok"
                if tl["rung"] != "fast" or any(
                        not a["ok"] for a in tl["attempts"]):
                    degraded += 1
        assert degraded > 0, "the fault burst never degraded a request"

    def test_expired_requests_own_consistent_timelines(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        rec = FlightRecorder(capacity=64)
        # A coalescing window far past the deadline: expiry in queue is
        # deterministic.
        with MicroBatcher(model, max_batch=64, max_wait_ms=2000.0,
                          recorder=rec) as batcher:
            h = batcher.submit(test.features[:1], "predict", deadline_ms=20)
            with pytest.raises(DeadlineExceededError):
                h.result(timeout=30)
            rid = h.meta["request_id"]
            deadline = __import__("time").monotonic() + 10
            while rec.find(rid) is None and \
                    __import__("time").monotonic() < deadline:
                __import__("time").sleep(0.01)
        tl = rec.find(rid)
        assert tl is not None and tl["outcome"] == "expired"
        self._check_timeline(tl)
        assert tl["expired_where"] == "queue"

    def test_expired_mid_fallback_timeline(self, rng, obs_on, monkeypatch):
        """A deadline that passes WHILE a higher rung is failing: the 504's
        timeline must show the failed attempt, name the expiry site, and
        still sum consistently; the deadline-free batchmate's timeline
        records the whole ladder walk down to the rung that answered."""
        import time as _time

        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)

        def slow_boom(ds):
            _time.sleep(0.4)
            from knn_tpu.resilience.errors import DeviceError
            raise DeviceError("slowly dying device")

        rec = FlightRecorder(capacity=16)
        b = MicroBatcher(model, max_batch=64, max_wait_ms=50.0, recorder=rec)
        try:
            monkeypatch.setattr(model, "kneighbors", slow_boom)
            ha = b.submit(test.features[0], deadline_ms=200)
            hb = b.submit(test.features[1])
            with pytest.raises(DeadlineExceededError, match="degradation"):
                ha.result(timeout=60)
            hb.result(timeout=60)
        finally:
            monkeypatch.undo()
            b.close()
        expired = rec.find(ha.meta["request_id"])
        assert expired["outcome"] == "expired"
        assert expired["expired_where"] == "mid-fallback"
        assert [a["ok"] for a in expired["attempts"]] == [False]
        assert expired["attempts"][0]["rung"] == "fast"
        self._check_timeline(expired)
        survivor = rec.find(hb.meta["request_id"])
        assert survivor["outcome"] == "ok" and survivor["rung"] == "oracle"
        assert [a["rung"] for a in survivor["attempts"]] == \
            ["fast", "oracle"]
        assert any(e["event"] == "fallback" for e in survivor["events"])
        self._check_timeline(survivor)

    def test_rejected_submission_resolves_too(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        rec = FlightRecorder(capacity=8)
        with MicroBatcher(model, max_batch=2, max_queue_rows=2,
                          max_wait_ms=2000.0, recorder=rec) as batcher:
            # One row parks in the 2 s coalescing window; two more rows on
            # top exceed the queue bound deterministically.
            parked = batcher.submit(test.features[:1], "predict")
            with pytest.raises(OverloadError):
                batcher.submit(test.features[1:3], "predict")
            parked.result(timeout=30)
        rejected = [tl for tl in rec.recent() if tl["outcome"] == "rejected"]
        assert len(rejected) == 1
        assert "OverloadError" in rejected[0]["error"]

    def test_meta_carries_request_id(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        rec = FlightRecorder(capacity=8)
        with MicroBatcher(model, max_batch=4, max_wait_ms=0.5,
                          recorder=rec) as batcher:
            h = batcher.submit(test.features[:1], "predict")
            h.result(timeout=60)
        assert rec.find(h.meta["request_id"])["outcome"] == "ok"

    def test_no_recorder_means_no_traces(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        with MicroBatcher(model, max_batch=4, max_wait_ms=0.5) as batcher:
            h = batcher.submit(test.features[:1], "predict")
            h.result(timeout=60)
        assert "request_id" not in h.meta


# ---------------------------------------------------------------------------
# The HTTP weave


def _get(base, path, headers=None):
    req = urllib.request.Request(base + path, headers=headers or {})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read().decode()


def _post(base, path, payload, headers=None):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


@pytest.fixture
def served(rng, obs_on, tmp_path):
    """A warmed in-process server with tracing + access log on."""
    from knn_tpu.obs.slo import SLOTracker
    from knn_tpu.serve.server import ServeApp, make_server

    train, test = _problem(rng)
    model = KNNClassifier(k=3, engine="xla").fit(train)
    log_path = tmp_path / "access.log"
    app = ServeApp(model, max_batch=16, max_wait_ms=1.0,
                   access_log=str(log_path),
                   slo=SLOTracker(windows_s=(5, 60)))
    server = make_server(app)
    host, port = server.server_address[:2]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    app.warm((1, 4))
    try:
        yield f"http://{host}:{port}", model, test, app, log_path
    finally:
        server.shutdown()
        server.server_close()
        app.close()
        thread.join(timeout=10)


class TestServerRequestIds:
    def test_request_id_echoed_on_success(self, served):
        base, _, test, _, _ = served
        st, hdrs, body = _post(base, "/predict",
                               {"instances": test.features[:2].tolist()},
                               headers={"x-request-id": "caller-1"})
        assert st == 200
        assert hdrs.get("x-request-id") == "caller-1"
        assert body["request_id"] == "caller-1"

    def test_request_id_generated_when_absent(self, served):
        base, _, test, _, _ = served
        st, hdrs, body = _post(base, "/predict",
                               {"instances": test.features[:1].tolist()})
        assert st == 200
        assert valid_request_id(body["request_id"])
        assert hdrs.get("x-request-id") == body["request_id"]

    def test_request_id_on_error_bodies(self, served):
        base, _, test, _, _ = served
        # 400 bad body
        st, hdrs, body = _post(base, "/predict", {"rows": [[1.0]]},
                               headers={"x-request-id": "err-1"})
        assert st == 400 and body["request_id"] == "err-1"
        assert hdrs.get("x-request-id") == "err-1"
        # 404 unknown endpoint
        st, hdrs, body = _post(base, "/train", {"instances": []},
                               headers={"x-request-id": "err-2"})
        assert st == 404 and body["request_id"] == "err-2"
        # 404 on GET too
        st, hdrs, raw = _get(base, "/nope", headers={"x-request-id": "err-3"})
        assert st == 404 and json.loads(raw)["request_id"] == "err-3"

    @pytest.mark.parametrize("bad", ["x" * 4096, "has spaces here"])
    def test_malformed_header_is_400_not_traceback(self, served, bad):
        base, _, test, _, _ = served
        st, hdrs, body = _post(base, "/predict",
                               {"instances": test.features[:1].tolist()},
                               headers={"x-request-id": bad})
        assert st == 400
        assert "invalid x-request-id" in body["error"]
        # A fresh id is generated so even the rejection is traceable.
        assert valid_request_id(body["request_id"])


class TestDebugEndpoints:
    def test_resolve_and_slowest(self, served):
        base, _, test, _, _ = served
        _post(base, "/predict", {"instances": test.features[:2].tolist()},
              headers={"x-request-id": "dbg-1"})
        st, _, raw = _get(base, "/debug/requests?id=dbg-1")
        assert st == 200
        tl = json.loads(raw)["requests"][0]
        assert tl["outcome"] == "ok" and tl["status"] == 200
        assert {"queue_wait", "dispatch"} == \
            {p["phase"] for p in tl["phases"]}
        st, _, raw = _get(base, "/debug/slowest")
        assert st == 200 and json.loads(raw)["requests"]

    def test_unknown_id_404_and_bad_params_400(self, served):
        base = served[0]
        assert _get(base, "/debug/requests?id=missing")[0] == 404
        assert _get(base, "/debug/requests?format=xml")[0] == 400
        assert _get(base, "/debug/requests?n=zap")[0] == 400

    def test_perfetto_export(self, served):
        base, _, test, _, _ = served
        _post(base, "/predict", {"instances": test.features[:1].tolist()})
        st, _, raw = _get(base, "/debug/requests?format=perfetto")
        doc = json.loads(raw)
        ev = doc["traceEvents"]
        assert st == 200 and ev
        assert sum(1 for e in ev if e["ph"] == "B") == \
            sum(1 for e in ev if e["ph"] == "E")

    def test_disabled_recorder_is_404(self, rng, obs_on):
        from knn_tpu.serve.server import ServeApp, make_server

        train, _ = _problem(rng)
        app = ServeApp(KNNClassifier(k=3, engine="xla").fit(train),
                       flight_recorder_size=0)
        server = make_server(app)
        host, port = server.server_address[:2]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        try:
            st, _, raw = _get(f"http://{host}:{port}", "/debug/requests")
            assert st == 404 and "disabled" in json.loads(raw)["error"]
            assert app.batcher.recorder is None
        finally:
            server.shutdown()
            server.server_close()
            app.close()


class TestServerSLOAndAccessLog:
    def test_healthz_carries_slo_block(self, served):
        base, _, test, _, _ = served
        _post(base, "/predict", {"instances": test.features[:1].tolist()})
        st, _, raw = _get(base, "/healthz")
        h = json.loads(raw)
        assert st == 200
        burns = h["slo"]["burn_rates"]
        assert set(burns) == {"availability", "latency", "fast_rung",
                              "quality"}
        assert burns["availability"]["5s"] == 0.0  # all-200 traffic
        assert h["flight_recorder"]["completed"] >= 1

    def test_openmetrics_negotiation_with_exemplars(self, served):
        base, _, test, _, _ = served
        _post(base, "/predict", {"instances": test.features[:1].tolist()},
              headers={"x-request-id": "ex-1"})
        st, hdrs, raw = _get(base, "/metrics",
                             headers={"Accept":
                                      "application/openmetrics-text"})
        assert st == 200
        assert "application/openmetrics-text" in hdrs["Content-Type"]
        assert raw.rstrip().endswith("# EOF")
        assert 'trace_id="ex-1"' in raw
        # Default scrape stays plain Prometheus, exemplar-free.
        st, hdrs, raw = _get(base, "/metrics")
        assert "text/plain" in hdrs["Content-Type"] and "# {" not in raw

    def test_access_log_one_line_per_terminal_outcome(self, served):
        base, _, test, app, log_path = served
        _post(base, "/predict", {"instances": test.features[:2].tolist()},
              headers={"x-request-id": "log-ok"})
        _post(base, "/predict", {"rows": "bad"},
              headers={"x-request-id": "log-bad"})
        # The handler writes its line AFTER the response goes out, so the
        # client can observe the response before the line lands — poll
        # (bounded) instead of reading once.
        import time as _time

        by_id = {}
        deadline = _time.monotonic() + 10
        while _time.monotonic() < deadline:
            app.access_log._file.flush()
            entries = [json.loads(ln) for ln in
                       log_path.read_text().splitlines()]
            by_id = {e["request_id"]: e for e in entries}
            if {"log-ok", "log-bad"} <= by_id.keys():
                break
            _time.sleep(0.01)
        ok = by_id["log-ok"]
        assert (ok["status"], ok["outcome"], ok["kind"], ok["rows"]) == \
            (200, "ok", "predict", 2)
        assert ok["rung"] == "fast" and "queue_wait" in ok["phases"]
        bad = by_id["log-bad"]
        assert (bad["status"], bad["outcome"]) == (400, "invalid")

    def test_rejection_spends_availability_budget(self, rng, obs_on):
        from knn_tpu.obs.slo import SLOTracker
        from knn_tpu.serve.server import ServeApp, make_server

        train, test = _problem(rng)
        # A coalescing window far longer than the test: the parked 1-row
        # request holds the queue open however loaded the box is (a 2 s
        # window flaked under full-suite load); close() in the teardown
        # gives it a typed outcome, so the park thread always exits.
        app = ServeApp(KNNClassifier(k=3, engine="xla").fit(train),
                       max_batch=2, max_queue_rows=2, max_wait_ms=60000.0,
                       slo=SLOTracker(windows_s=(60,)))
        server = make_server(app)
        host, port = server.server_address[:2]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://{host}:{port}"
        t = None
        try:
            app.warm((1,))

            def park():
                _post(base, "/predict",
                      {"instances": test.features[:1].tolist()})

            t = threading.Thread(target=park, daemon=True)
            t.start()
            import time as _time
            # Wait for the parked row to actually be QUEUED before
            # probing: if the 2-row probe wins admission first, it is the
            # PARK request that gets rejected (2+1 > bound), the park
            # thread exits, and no probe can ever overflow an empty
            # queue — the race this test flaked on under full-suite load.
            deadline = _time.monotonic() + 30
            while (_time.monotonic() < deadline
                   and app.batcher.pending_rows() == 0):
                _time.sleep(0.005)
            assert app.batcher.pending_rows() == 1
            st = None
            while _time.monotonic() < deadline:
                st, _, body = _post(
                    base, "/predict",
                    {"instances": test.features[1:3].tolist()})
                if st == 429:
                    break
                _time.sleep(0.01)
            assert st == 429
            # The SLO record lands on the handler thread AFTER the 429
            # response goes out (_account keeps bookkeeping off the hot
            # path) — poll, bounded, instead of asserting instantly.
            burn = 0.0
            deadline = _time.monotonic() + 10
            while _time.monotonic() < deadline:
                burn = app.slo.burn_rates()["availability"]["1m"]
                if burn > 0:
                    break
                _time.sleep(0.01)
            assert burn > 0
        finally:
            server.shutdown()
            server.server_close()
            app.close()
            if t is not None:
                t.join(timeout=30)
