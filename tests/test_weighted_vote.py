"""Distance-weighted classification voting (opt-in extension; the reference
vote is an unweighted bincount with lowest-class-id ties, main.cpp:64-78,
which stays the default)."""

import numpy as np
import pytest

from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier


def _problem(rng, n=300, q=40, d=5, c=6):
    train_x = rng.uniform(0, 10, (n, d)).astype(np.float32)
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, q // 2, replace=False)],
         rng.uniform(0, 10, (q - q // 2, d)).astype(np.float32)]
    )
    return Dataset(train_x, train_y), Dataset(test_x, np.zeros(q, np.int32))


class TestWeightedVote:
    def test_matches_manual_weighted_argmax(self, rng):
        train, test = _problem(rng)
        model = KNNClassifier(k=7, weights="distance").fit(train)
        got = model.predict(test)
        dists, idx = model.kneighbors(test)
        labels = train.labels[idx]
        want = np.empty(test.num_instances, np.int32)
        for i in range(test.num_instances):
            d = dists[i].astype(np.float64)
            if (d == 0).any():
                w = (d == 0).astype(np.float64)
            else:
                w = 1.0 / d
            scores = np.zeros(train.num_classes)
            for lbl, wt in zip(labels[i], w):
                scores[lbl] += wt
            want[i] = np.argmax(scores)
        np.testing.assert_array_equal(got, want)

    def test_exact_match_dominates(self):
        # Query equal to one train row: its class must win outright even
        # against k-1 very close neighbors of another class.
        train = Dataset(
            np.array([[0.0], [0.01], [0.02], [0.03]], np.float32),
            np.array([3, 1, 1, 1], np.int32),
        )
        test = Dataset(np.array([[0.0]], np.float32), np.zeros(1, np.int32))
        model = KNNClassifier(k=4, weights="distance").fit(train)
        assert model.predict(test)[0] == 3
        proba = model.predict_proba(test)
        assert proba[0, 3] == pytest.approx(1.0)

    def test_uniform_default_unchanged(self, rng):
        # weights="uniform" must stay bit-identical to the backend vote.
        train, test = _problem(rng)
        a = KNNClassifier(k=5).fit(train).predict(test)
        b = KNNClassifier(k=5, weights="uniform").fit(train).predict(test)
        np.testing.assert_array_equal(a, b)

    def test_proba_normalized(self, rng):
        train, test = _problem(rng)
        model = KNNClassifier(k=5, weights="distance").fit(train)
        proba = model.predict_proba(test)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-9)
        assert (proba >= 0).all()

    def test_bad_weights_rejected(self):
        with pytest.raises(ValueError, match="weights"):
            KNNClassifier(k=1, weights="rank")

    def test_backend_options_rejected_with_weighted_vote(self):
        # The weighted vote always uses the JAX candidate kernel; accepting a
        # backend choice and silently ignoring it would mislead.
        with pytest.raises(ValueError, match="silently ignored"):
            KNNClassifier(k=1, backend="native", weights="distance")
        with pytest.raises(ValueError, match="silently ignored"):
            KNNClassifier(k=1, weights="distance", precision="fast")
