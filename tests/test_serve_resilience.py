"""Self-healing serving contracts (docs/SERVING.md §Ops runbook):

- the circuit breaker state machine (closed/open/half-open over a sliding
  failure window) and its env knobs;
- in-loop degradation: a fast-rung device failure mid-serve degrades the
  BATCH (bit-identical answers from a lower rung) instead of failing it;
  OOM halves ``max_batch`` in place; the breaker short-circuits to the
  degraded rung while open and re-promotes after recovery probes;
- the supervisor restarting a dead worker thread (counted);
- deadline propagation through the ladder: a request expiring
  mid-fallback gets ``DeadlineExceededError``, not a slow success;
- shutdown under load: every admitted request ends with a typed terminal
  outcome, never a hung waiter;
- hot index reload: atomic swap (responses carry exactly the old or the
  new ``index_version``), rollback on a corrupt replacement;
- graceful drain: readiness flips, admissions refused typed, queued work
  answered (or failed typed when the window expires).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.resilience import faults
from knn_tpu.resilience.breaker import CircuitBreaker
from knn_tpu.resilience.errors import (
    DeadlineExceededError, DeviceError, OverloadError,
)
from knn_tpu.serve import artifact
from knn_tpu.serve.batcher import MicroBatcher
from knn_tpu.serve.server import ServeApp, make_server


def _problem(rng, n=300, q=40, d=5, c=5):
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)  # grid -> ties
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, q // 2, replace=False)],
         rng.integers(0, 4, (q - q // 2, d)).astype(np.float32)]
    )
    train = Dataset(train_x, train_y)
    test = Dataset(test_x, np.zeros(len(test_x), np.int32))
    return train, test


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


# ---------------------------------------------------------------------------
# CircuitBreaker state machine


class TestCircuitBreaker:
    def test_trips_open_at_threshold(self):
        b = CircuitBreaker("t", window=4, threshold=2, cooldown_ms=10_000,
                           probe_successes=1)
        assert b.decide() == "closed"
        b.record_failure()
        assert b.state == "closed"
        b.record_failure()
        assert b.state == "open"
        assert b.decide() == "open"  # within cooldown: short-circuit
        assert b.short_circuits == 1

    def test_window_slides_failures_out(self):
        b = CircuitBreaker("t", window=3, threshold=2, cooldown_ms=10_000)
        b.record_failure()
        b.record_success()
        b.record_success()
        b.record_success()  # the failure aged out of the 3-wide window
        b.record_failure()
        assert b.state == "closed"  # 1 failure in window, threshold 2

    def test_half_open_probe_recloses_after_successes(self):
        b = CircuitBreaker("t", window=4, threshold=1, cooldown_ms=1,
                           probe_successes=2)
        b.record_failure()
        assert b.state == "open"
        time.sleep(0.005)
        assert b.decide() == "probe"
        b.record_success()
        assert b.state == "half-open"  # 1 of 2 probes
        assert b.decide() == "probe"
        b.record_success()
        assert b.state == "closed"

    def test_failed_probe_reopens(self):
        b = CircuitBreaker("t", window=4, threshold=1, cooldown_ms=1,
                           probe_successes=1)
        b.record_failure()
        time.sleep(0.005)
        assert b.decide() == "probe"
        b.record_failure()
        assert b.state == "open"
        assert b.decide() == "open"  # cooldown restarted

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("KNN_TPU_BREAKER_WINDOW", "7")
        monkeypatch.setenv("KNN_TPU_BREAKER_THRESHOLD", "3")
        monkeypatch.setenv("KNN_TPU_BREAKER_COOLDOWN_MS", "123")
        monkeypatch.setenv("KNN_TPU_BREAKER_PROBES", "4")
        b = CircuitBreaker("env")
        assert (b.window, b.threshold, b.cooldown_ms, b.probe_successes) == \
            (7, 3, 123.0, 4)

    def test_bad_config_rejected(self):
        with pytest.raises(ValueError, match="threshold"):
            CircuitBreaker("t", window=2, threshold=5)

    def test_transition_metrics(self, obs_on):
        b = CircuitBreaker("m", window=2, threshold=1, cooldown_ms=10_000)
        b.record_failure()
        assert obs_on.counter(
            "knn_breaker_transitions_total", breaker="m", from_state="closed",
            to_state="open",
        ).value == 1
        assert obs_on.gauge("knn_breaker_state", breaker="m").value == 1
        b.decide()
        assert obs_on.counter(
            "knn_breaker_short_circuits_total", breaker="m").value == 1


# ---------------------------------------------------------------------------
# In-loop degradation


class TestServingLadder:
    def test_fast_failure_degrades_bit_identical(self, rng, obs_on,
                                                 monkeypatch):
        """A persistent device failure mid-serve must NOT fail the batch:
        the ladder answers from a lower rung with bit-identical
        predictions, counted as a fallback."""
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        want = model.predict(test)

        def boom(ds):
            raise DeviceError("dead device")

        with MicroBatcher(model, max_batch=64, max_wait_ms=1.0) as b:
            monkeypatch.setattr(model, "kneighbors", boom)
            h = b.submit(test.features)
            got = h.result(timeout=60)
        np.testing.assert_array_equal(got, want)
        assert h.meta["rung"] == "oracle"  # engine=xla ladder: fast→oracle
        assert obs_on.counter(
            "knn_serve_fallback_total", from_rung="fast", to="oracle",
            reason="DeviceError",
        ).value >= 1

    def test_kneighbors_degrades_with_identical_indices(self, rng,
                                                        monkeypatch):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        _, want_i = model.kneighbors(test)

        def boom(ds):
            raise DeviceError("dead device")

        with MicroBatcher(model, max_batch=64, max_wait_ms=1.0) as b:
            monkeypatch.setattr(model, "kneighbors", boom)
            _, got_i = b.kneighbors(test.features, timeout=60)
        np.testing.assert_array_equal(got_i, want_i)

    def test_injected_oom_halves_max_batch_in_place(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        want = model.predict(test)
        with MicroBatcher(model, max_batch=8, max_wait_ms=1.0) as b:
            with faults.inject("serve.dispatch=once:oom"):
                got = b.predict(test.features[:4], timeout=60)
            assert b.max_batch == 4  # halved in place, not failed
            np.testing.assert_array_equal(got, want[:4])
        assert obs_on.counter(
            "knn_serve_fallback_total", from_rung="fast", to="fast",
            reason="oom_halve_batch",
        ).value == 1

    def test_breaker_opens_short_circuits_and_recloses(self, rng, obs_on,
                                                       monkeypatch):
        """The full self-healing cycle: sustained fast-rung faults trip
        the breaker open (requests keep succeeding, served degraded and
        short-circuited past the doomed dispatch); once the faults clear
        and the cooldown elapses, a half-open probe re-promotes the fast
        rung."""
        monkeypatch.setenv("KNN_TPU_BREAKER_WINDOW", "4")
        monkeypatch.setenv("KNN_TPU_BREAKER_THRESHOLD", "2")
        monkeypatch.setenv("KNN_TPU_BREAKER_COOLDOWN_MS", "250")
        monkeypatch.setenv("KNN_TPU_BREAKER_PROBES", "1")
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        want = model.predict(test)
        model.kneighbors(test)  # warm outside the fault window
        b = MicroBatcher(model, max_batch=4, max_wait_ms=0.5)
        try:
            with faults.inject("serve.dispatch=always"):
                for i in range(6):
                    np.testing.assert_array_equal(
                        b.predict(test.features[i], timeout=60), want[i]
                    )
                assert b.breaker.state == "open"
                assert b.breaker.short_circuits >= 1  # degraded, not doomed
            # Faults cleared: after the cooldown the next dispatch is a
            # half-open probe that succeeds and re-closes the breaker.
            time.sleep(0.3)
            h = b.submit(test.features[0])
            np.testing.assert_array_equal(h.result(timeout=60), want[0])
            assert b.breaker.state == "closed"
            assert h.meta["rung"] == "fast"  # re-promoted
        finally:
            b.close()
        assert obs_on.counter(
            "knn_breaker_transitions_total", breaker="serve.dispatch",
            from_state="half-open", to_state="closed",
        ).value >= 1

    def test_deadline_expires_mid_fallback(self, rng, obs_on, monkeypatch):
        """A request whose deadline passes while a higher rung is failing
        gets its 504 — never a slow success from a lower rung. A
        deadline-free request in the same batch still gets the degraded
        (bit-identical) answer."""
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        want = model.predict(test)

        def slow_boom(ds):
            time.sleep(0.4)
            raise DeviceError("slowly dying device")

        b = MicroBatcher(model, max_batch=64, max_wait_ms=50.0)
        try:
            monkeypatch.setattr(model, "kneighbors", slow_boom)
            ha = b.submit(test.features[0], deadline_ms=200)
            hb = b.submit(test.features[1])
            with pytest.raises(DeadlineExceededError, match="degradation"):
                ha.result(timeout=60)
            np.testing.assert_array_equal(hb.result(timeout=60), want[1])
            assert hb.meta["rung"] == "oracle"
        finally:
            monkeypatch.undo()
            b.close()
        assert obs_on.counter("knn_serve_deadline_expired_total").value == 1

    def test_supervisor_restarts_dead_worker(self, rng, obs_on):
        """A worker whose own machinery dies is restarted (counted), and
        queued requests are served by the replacement instead of hanging
        until their timeouts."""
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        want = model.predict(test)
        b = MicroBatcher(model, max_batch=8, max_wait_ms=1.0)
        try:
            orig = b._collect
            died = {"n": 0}

            def dying_collect():
                if died["n"] == 0:
                    died["n"] = 1
                    raise RuntimeError("synthetic worker death")
                return orig()

            b._collect = dying_collect
            # The original worker is blocked inside the old _collect; this
            # request is served by it, then the NEXT loop iteration hits
            # the dying replacement and kills the worker.
            np.testing.assert_array_equal(
                b.predict(test.features[0], timeout=60), want[0]
            )
            deadline = time.monotonic() + 10
            while b.restarts == 0 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert b.restarts == 1, "supervisor never restarted the worker"
            np.testing.assert_array_equal(
                b.predict(test.features[1], timeout=60), want[1]
            )
        finally:
            b.close()
        assert obs_on.counter("knn_serve_worker_restarts_total").value == 1


# ---------------------------------------------------------------------------
# Shutdown under load


class TestShutdownUnderLoad:
    def test_close_under_load_leaves_typed_outcomes(self, rng, monkeypatch):
        """close() racing an in-flight dispatch: every admitted request
        must end with a value or a TYPED error — a waiter that hits its
        own wait-timeout ("not served within") means a silently dropped
        request, the regression this pins."""
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        model.kneighbors(test)  # warm so the slow path is the sleep
        real = model.kneighbors

        def slow(ds):
            time.sleep(0.1)
            return real(ds)

        monkeypatch.setattr(model, "kneighbors", slow)
        b = MicroBatcher(model, max_batch=1, max_wait_ms=0.0)
        handles = [b.submit(test.features[i]) for i in range(8)]
        time.sleep(0.05)  # let the worker start dispatching the head
        b.close(timeout=0.25)  # expires with most of the queue undrained
        served, failed = 0, 0
        for h in handles:
            try:
                assert h.result(timeout=5) is not None
                served += 1
            except OverloadError:
                failed += 1  # typed shutdown outcome — the contract
            except DeadlineExceededError as e:
                assert "not served within" not in str(e), (
                    "a waiter hung: request dropped without a terminal "
                    "outcome"
                )
                failed += 1
        assert served + failed == 8
        assert failed > 0, "close(timeout) drained everything; the race " \
                           "this test exists for never happened"


# ---------------------------------------------------------------------------
# Hot reload + drain (HTTP level)


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def two_indexes(rng, tmp_path):
    train, test = _problem(rng)
    idx_a = artifact.save_index(
        KNNClassifier(k=1, engine="xla").fit(train), tmp_path / "a")
    idx_b = artifact.save_index(
        KNNClassifier(k=5, engine="xla").fit(train), tmp_path / "b")
    return train, test, idx_a, idx_b


@pytest.fixture
def reload_server(two_indexes, obs_on):
    train, test, idx_a, idx_b = two_indexes
    model = artifact.load_index(idx_a)
    version = artifact.index_version(artifact.read_manifest(idx_a))
    app = ServeApp(model, max_batch=16, max_wait_ms=1.0,
                   index_path=str(idx_a), index_version=version)
    server = make_server(app)
    host, port = server.server_address[:2]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    app.warm((1, 4))
    try:
        yield f"http://{host}:{port}", app, test, idx_a, idx_b, version
    finally:
        server.shutdown()
        server.server_close()
        app.close()
        thread.join(timeout=10)


class TestHotReload:
    def test_reload_swaps_version_atomically(self, reload_server):
        base, app, test, idx_a, idx_b, va = reload_server
        want_b = artifact.load_index(idx_b).predict(test).tolist()
        st, h = _get(base, "/healthz")
        assert st == 200 and json.loads(h)["index_version"] == va
        st, body = _post(base, "/admin/reload", {"index": str(idx_b)})
        assert st == 200, body
        vb = body["index_version"]
        assert vb != va and body["previous_version"] == va
        assert body["warmup_ms"]  # the new index warmed before the swap
        st, h = _get(base, "/healthz")
        assert json.loads(h)["index_version"] == vb
        st, body = _post(base, "/predict",
                         {"instances": test.features.tolist()})
        assert st == 200
        assert body["index_version"] == vb
        assert body["predictions"] == want_b

    def test_corrupt_replacement_rolls_back(self, reload_server):
        base, app, test, idx_a, idx_b, va = reload_server
        want_a = artifact.load_index(idx_a).predict(test).tolist()
        (idx_b / "arrays.npz").write_bytes(b"not a zip archive")
        st, body = _post(base, "/admin/reload", {"index": str(idx_b)})
        assert st == 400, body
        assert body["rolled_back"] is True
        assert body["index_version"] == va  # the old index still serving
        st, body = _post(base, "/predict",
                         {"instances": test.features.tolist()})
        assert st == 200
        assert body["index_version"] == va
        assert body["predictions"] == want_a

    def test_family_change_rejected(self, reload_server, rng, tmp_path):
        from knn_tpu.models.knn import KNNRegressor

        base, app, test, idx_a, idx_b, va = reload_server
        train, _ = _problem(rng)
        reg_train = Dataset(
            train.features, train.labels,
            raw_targets=rng.standard_normal(
                train.num_instances).astype(np.float32),
        )
        reg_idx = artifact.save_index(
            KNNRegressor(k=3).fit(reg_train), tmp_path / "reg")
        st, body = _post(base, "/admin/reload", {"index": str(reg_idx)})
        assert st == 400 and "family" in body["error"]
        assert app.index_version == va

    def test_reload_under_load_never_serves_a_mix(self, reload_server):
        """Concurrent predicts during a reload: every response must carry
        exactly the old or the new index_version, with predictions
        matching THAT version's model — never a mix."""
        base, app, test, idx_a, idx_b, va = reload_server
        want_a = artifact.load_index(idx_a).predict(test).tolist()
        want_b = artifact.load_index(idx_b).predict(test).tolist()
        assert want_a != want_b, "k=1 vs k=5 must disagree somewhere or " \
                                 "this test proves nothing"
        rows = test.features.tolist()
        results, errors = [], []
        stop = threading.Event()

        def client():
            while not stop.is_set():
                try:
                    st, body = _post(base, "/predict", {"instances": rows})
                    if st != 200:
                        errors.append((st, body))
                    else:
                        results.append(
                            (body["index_version"], body["predictions"]))
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors.append(("exc", repr(e)))

        threads = [threading.Thread(target=client) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.2)
        st, body = _post(base, "/admin/reload", {"index": str(idx_b)})
        vb = body["index_version"]
        time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert st == 200, body
        assert not errors, errors[:3]
        seen = set()
        for version, preds in results:
            assert version in (va, vb), f"unknown index_version {version}"
            want = want_a if version == va else want_b
            assert preds == want, (
                f"response tagged {version} did not match that version's "
                f"model — a mixed index was served"
            )
            seen.add(version)
        assert va in seen, "no response from the old index — load never " \
                           "overlapped the reload"

    def test_concurrent_reload_conflicts_409(self, reload_server,
                                             monkeypatch):
        base, app, test, idx_a, idx_b, va = reload_server
        release = threading.Event()
        real_warm = artifact.warmup

        def slow_warm(*a, **kw):
            release.wait(10)
            return real_warm(*a, **kw)

        monkeypatch.setattr(artifact, "warmup", slow_warm)
        first = {}

        def kick():
            first["resp"] = _post(base, "/admin/reload",
                                  {"index": str(idx_b)})

        t = threading.Thread(target=kick)
        t.start()
        # Wait until the in-flight reload actually holds the reload lock
        # (blocked inside the slowed warmup) before probing.
        deadline = time.monotonic() + 10
        while (not app._reload_lock.locked()
               and time.monotonic() < deadline):
            time.sleep(0.005)
        assert app._reload_lock.locked()
        st, body = _post(base, "/admin/reload", {"index": str(idx_b)})
        release.set()
        t.join(timeout=30)
        assert st == 409, body
        assert first["resp"][0] == 200  # the in-flight reload completed


class TestDrain:
    def test_drain_flips_health_refuses_and_answers(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        # A long coalescing window parks the request so drain overlaps it.
        app = ServeApp(model, max_batch=64, max_wait_ms=2000.0)
        server = make_server(app)
        host, port = server.server_address[:2]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://{host}:{port}"
        try:
            app.warm((1,))
            parked = {}

            def park():
                parked["resp"] = _post(base, "/predict", {
                    "instances": [test.features[0].tolist()]})

            t = threading.Thread(target=park)
            t.start()
            deadline = time.monotonic() + 10
            while (app.batcher.pending_rows() == 0
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert app.batcher.pending_rows() > 0
            summary = {}
            dt = threading.Thread(
                target=lambda: summary.update(app.drain(10.0)))
            dt.start()
            deadline = time.monotonic() + 10
            while not app.draining and time.monotonic() < deadline:
                time.sleep(0.01)
            st, h = _get(base, "/healthz")
            assert st == 503 and json.loads(h)["draining"] is True
            st, body = _post(base, "/predict", {
                "instances": [test.features[1].tolist()]})
            assert st == 503 and "draining" in body["error"]
            dt.join(timeout=30)
            t.join(timeout=30)
            # The parked request was ANSWERED during the drain (the drain
            # cuts the coalescing window short), not dropped.
            assert parked["resp"][0] == 200
            assert summary["drained_clean"] is True
            assert summary["expired"] == 0
        finally:
            server.shutdown()
            server.server_close()
            app.close()

    def test_expired_drain_fails_remainders_typed(self, rng, obs_on,
                                                  monkeypatch):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        model.kneighbors(test)  # warm
        real = model.kneighbors

        def slow(ds):
            time.sleep(0.5)
            return real(ds)

        monkeypatch.setattr(model, "kneighbors", slow)
        app = ServeApp(model, max_batch=1, max_wait_ms=0.0)
        handles = [app.batcher.submit(test.features[i]) for i in range(6)]
        summary = app.drain(timeout_s=0.2)
        assert summary["expired"] > 0
        for h in handles:
            try:
                assert h.result(timeout=5) is not None
            except DeadlineExceededError as e:
                # The typed expired-drain outcome, NOT a hung waiter
                # timing out on its own wait.
                assert "not served within" not in str(e), "a waiter hung"
                assert "drained" in str(e)
        # fail_pending clearing the queue under the worker must NOT read
        # as a worker death: no bogus restart counted on a routine drain.
        time.sleep(0.7)  # let the in-flight slow dispatch finish its loop
        assert app.batcher.restarts == 0
        app.close()
