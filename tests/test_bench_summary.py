"""The bench artifact must stay machine-readable (VERDICT r4 #1).

The driver captures only the last ~2 KB of bench.py stdout; round 4 lost
its headline because the single full-record JSON line outgrew that window
(`BENCH_r04.json` has ``parsed: null``). These tests pin the contract:
``compact_summary`` keeps every config's median fields, drops trial
lists, and serializes below ``bench.SUMMARY_BUDGET`` even when fed a
record with worst-case-long trial lists and every optional field present.
"""

import importlib.util
import json
import sys
from pathlib import Path

import pytest

_BENCH = Path(__file__).resolve().parent.parent / "bench.py"


@pytest.fixture(scope="module")
def bench():
    spec = importlib.util.spec_from_file_location("bench", _BENCH)
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("bench", mod)
    spec.loader.exec_module(mod)
    return mod


def _fat_record():
    """A full record with every field bench.py can emit, trial lists longer
    than any real run produces, and maximally wide float reprs — the
    worst case the summary must still compress below budget."""
    trials = [103.123456789 + i for i in range(12)]

    def spread(prefix=""):
        return {
            f"{prefix}step_ms": 1234.123,
            f"{prefix}step_ms_median": 1234.456,
            f"{prefix}step_ms_trials": trials,
        }

    return {
        "metric": "large_k5_query_throughput",
        "value": 2289144.2,
        "unit": "queries/sec",
        "vs_baseline": 16516.2,
        "accuracy": 0.9948,
        **spread(),
        "approx_topk_qps": 1234567.8,
        "approx_topk_accuracy": 0.9948,
        "configs": {
            "mnist784": {
                "metric": "mnist784_k5_query_throughput",
                "value": 1001234.5, "unit": "queries/sec",
                "vs_baseline": None, "tflops": 103.4, **spread(),
                "bf16_qps": 1071234.5, "bf16_tflops": 110.7,
                **spread("bf16_"), "bf16_engine": "stripe(1024,2048)",
                "bf16_recall_at_k": 0.9996,
                "bf16_matmul_tflops": 180.2, "bf16_matmul_ms": 1.168,
            },
            "xl": {
                "metric": "xl_1M_k10_query_throughput",
                "value": 51234.5, "unit": "queries/sec", "vs_baseline": None,
                "train_rows": 1016499, "dist_evals_per_sec": 51.2,
                "dist_unit": "Gdist/s", **spread(),
                "approx_qps": 512345.6, "approx_recall_at_k": 0.9234,
                "approx_dataset": "random 1M x 11 " * 5,
                "approx_step_ms_trials": trials, "approx_wins": True,
            },
            "xxl": {
                "metric": "xxl_10M_k5_query_throughput",
                "value": 8565.8, "unit": "queries/sec", "vs_baseline": None,
                "train_rows": 10010975, "dist_evals_per_sec": 85.8,
                "dist_unit": "Gdist/s", **spread(), "paths_agree": True,
            },
            "ingest": {
                "metric": "arff_ingest_throughput", "value": 309.1,
                "unit": "MB/s", "vs_baseline": None, "file_mb": 1.81,
                "native_mb_per_s": 309.1, "native_rows_per_s": 5264823,
                "native_ms_trials": trials, "python_mb_per_s": 15.7,
                "python_ms_trials": trials, "native_xl_file_mb": 90.4,
                "native_xl_mb_per_s": 831.4, "native_xl_ms_trials": trials,
            },
            "sharded": {
                "metric": "large_k5_sharded_query_throughput",
                "value": 2289144.2, "unit": "queries/sec",
                "vs_baseline": 16516.2, "accuracy": 0.9948, **spread(),
                "mesh": "1-device shard_map, stripe engine",
            },
            "kneighbors": {
                "metric": "large_k5_kneighbors_wall_throughput",
                "value": 16711.4, "unit": "queries/sec", "vs_baseline": None,
                "auto_ms_per_call": 102.8, "auto_ms_trials": trials,
                "xla_ms_per_call": 112.9, "xla_ms_trials": trials,
                "large_q": 109952, "large_q_qps": 1494039.6,
                "large_q_ms_trials": trials,
                "pipelined_ms_per_call": 12.3,
                "pipelined_ms_trials": trials,
            },
            "sweepk": {
                "metric": "sweepk_vs_single_cost", "value": 0.86,
                "unit": "sweep_wall / single_k10_wall", "vs_baseline": None,
                "large_accuracies": {"1": 0.9919, "5": 0.9948, "10": 0.7538},
                "prefix_equivalence": True,
                "large_sweep_ms": 176.5, "large_three_runs_ms": 607.8,
                "large_single_k10_ms": 204.9,
                "large_sweep_ms_trials": trials,
                "large_single_k10_ms_trials": trials,
                "xl_1M_sweep_ms": 234.0, "xl_1M_three_runs_ms": 676.2,
                "xl_1M_single_k10_ms": 233.4,
                "xl_1M_sweep_ms_trials": trials,
                "xl_1M_single_k10_ms_trials": trials,
            },
            "serving": {
                "metric": "serving_c8_batched_p50_ms", "value": 11.23,
                "unit": "ms", "vs_baseline": None, "train_rows": 7509,
                "max_batch": 64, "max_wait_ms": 2.0,
                "requests_per_client": 30,
                "levels": {
                    str(c): {
                        "batched_p50_ms": 11.23, "batched_p99_ms": 40.56,
                        "batched_qps": 1234.5, "seq_p50_ms": 101.89,
                        "seq_p99_ms": 250.12, "seq_qps": 98.7,
                        "mean_batch_requests": 7.89,
                    } for c in (1, 4, 8, 16)
                },
                "c8_batched_p50_ms": 11.23, "c8_seq_p50_ms": 101.89,
                "c8_batched_qps": 1234.5, "c8_seq_qps": 98.7,
                "batched_beats_seq_c8": True, "dropped_requests": 0,
                "deadline_expired": 0, "failed_requests": 0,
                "c8_occupancy_mean": 0.1234,
                "c8_padded_row_waste_ratio": 0.9876,
                "c8_duty_cycle": 0.9876,
            },
        },
    }


def test_summary_fits_tail_capture(bench):
    line = json.dumps(bench.compact_summary(_fat_record()))
    assert len(line) < bench.SUMMARY_BUDGET, (
        f"compact summary is {len(line)} B, budget {bench.SUMMARY_BUDGET}; "
        "trim _SUMMARY_EXTRA or the artifact goes unparseable again"
    )


def test_summary_keeps_headline_and_medians(bench):
    s = bench.compact_summary(_fat_record())
    assert s["metric"] == "large_k5_query_throughput"
    assert s["value"] == 2289144.2
    assert s["vs_baseline"] == 16516.2
    assert s["accuracy"] == 0.9948
    assert s["step_ms_median"] == 1234.456
    for name in ("mnist784", "xl", "xxl", "ingest", "sharded",
                 "kneighbors", "sweepk", "serving"):
        assert "value" in s["configs"][name], name
        # Dropped as redundant with the config name (budget headroom).
        assert "metric" not in s["configs"][name]
    assert s["configs"]["mnist784"]["bf16_tflops"] == 110.7
    assert s["configs"]["xl"]["dist_evals_per_sec"] == 51.2
    assert s["configs"]["sharded"]["accuracy"] == 0.9948
    # The serving row keeps the self-diagnosis counters and the win bit.
    assert s["configs"]["serving"]["batched_beats_seq_c8"] is True
    assert s["configs"]["serving"]["dropped_requests"] == 0
    assert s["configs"]["serving"]["deadline_expired"] == 0
    # Trial lists must NOT survive into the summary.
    assert "step_ms_trials" not in json.dumps(s)
    # Nor the serving config's per-level breakdown.
    assert "levels" not in s["configs"]["serving"]


def test_summary_truncates_config_errors(bench):
    rec = _fat_record()
    rec["configs"]["xl"] = {"error": "RuntimeError: " + "x" * 500}
    s = bench.compact_summary(rec)
    assert len(s["configs"]["xl"]["error"]) <= 120
    line = json.dumps(s)
    assert len(line) < bench.SUMMARY_BUDGET
