"""Overload control plane contract tests (docs/RESILIENCE.md §Degradation
order).

The load-bearing claims: priority admission sheds the LOWEST-priority
class first — and never the protected top tier — when headroom collapses;
every shed is a typed :class:`ShedByPolicy` with an actionable,
bounded ``Retry-After``; a policy shed never spends the availability
budget; the brownout ladder applies steps in order under pressure and
reverts every one of them (LIFO) on recovery, on a fake clock with zero
sleeps; the batch autotuner refuses ANY candidate the replay pass cannot
prove bit-identical, restoring the live window; and the autoscale policy
is a pure hysteresis over (offered, sustainable, usable) that never acts
without a fitted capacity model.
"""

import threading

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.control.admission import (
    RETRY_AFTER_MAX_S,
    RETRY_AFTER_MIN_S,
    PriorityAdmission,
    parse_priority_map,
)
from knn_tpu.control.autoscale import AutoscalePolicy, run_scale_cmd
from knn_tpu.control.autotune import BatchAutotuner
from knn_tpu.control.brownout import BrownoutController, BrownoutStep
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.obs.slo import SLOTracker
from knn_tpu.resilience.degrade import DEGRADATION_ORDER
from knn_tpu.resilience.errors import DataError, ShedByPolicy
from knn_tpu.serve.batcher import MicroBatcher


class FakeCapacity:
    """A capacity tracker stub: exports exactly the fields the control
    plane reads, with an operator-settable headroom."""

    def __init__(self, headroom=None, dispatch_model=None):
        self.headroom = headroom
        self.dispatch_model = dispatch_model

    def export(self):
        return {"headroom_ratio": self.headroom,
                "dispatch_model": self.dispatch_model}


def fresh_admission(priority_map, capacity, **kw):
    """An admission cutoff with the lazy-evaluation caches disabled so
    every admit() re-reads the (fake) signal and may move immediately."""
    kw.setdefault("eval_ms", 0.0)
    kw.setdefault("cooldown_ms", 0.0)
    return PriorityAdmission(priority_map, capacity=capacity, **kw)


class TestParsePriorityMap:
    def test_parses_classes_and_levels(self):
        assert parse_priority_map("interactive=0,bulk=2") == {
            "interactive": 0, "bulk": 2}

    def test_whitespace_and_trailing_comma_tolerated(self):
        assert parse_priority_map(" a=1 , b=0 ,") == {"a": 1, "b": 0}

    @pytest.mark.parametrize("spec", [
        "",                       # empty map
        "interactive",            # no '='
        "interactive=fast",       # non-integer priority
        "interactive=-1",         # negative priority
        "a=1,a=2",                # duplicate class
        "BAD CLASS=1",            # label grammar violation
    ])
    def test_bad_specs_raise_with_context(self, spec):
        with pytest.raises(ValueError):
            parse_priority_map(spec)


class TestPriorityShedOrdering:
    def test_no_pressure_admits_everything(self):
        adm = fresh_admission({"interactive": 0, "bulk": 2},
                              FakeCapacity(headroom=3.0))
        assert adm.admit("bulk") is None
        assert adm.admit("interactive") is None
        assert adm.export()["shed_tiers"] == 0

    def test_negative_headroom_sheds_lowest_tier_first(self):
        cap = FakeCapacity(headroom=0.4)  # offered 2.5x sustainable
        # A long cooldown freezes the cutoff after its FIRST move, so
        # the one-tier-at-a-time ordering is observable: bulk sheds,
        # batch and interactive still admit.
        adm = fresh_admission(
            {"interactive": 0, "batch": 1, "bulk": 2}, cap,
            cooldown_ms=3600_000.0)
        shed = adm.admit("bulk")
        assert isinstance(shed, ShedByPolicy)
        assert shed.request_class == "bulk"
        assert adm.admit("batch") is None
        assert adm.admit("interactive") is None
        assert adm.export()["shed_tiers"] == 1

    def test_sustained_pressure_walks_to_the_protected_cap(self):
        cap = FakeCapacity(headroom=0.4)
        adm = fresh_admission(
            {"interactive": 0, "batch": 1, "bulk": 2}, cap)
        # Cooldown 0: every decision may walk a tier. Pressure that
        # never lifts sheds batch too — but the top tier NEVER sheds
        # by policy, however long pressure holds.
        assert isinstance(adm.admit("bulk"), ShedByPolicy)
        assert isinstance(adm.admit("batch"), ShedByPolicy)
        for _ in range(8):
            assert adm.admit("interactive") is None
        assert adm.export()["shed_tiers"] == 2  # capped at len(levels)-1

    def test_unmapped_class_defaults_to_protected(self):
        adm = fresh_admission({"interactive": 0, "bulk": 2},
                              FakeCapacity(headroom=0.2))
        assert isinstance(adm.admit("bulk"), ShedByPolicy)
        # Mapping interactive=0,bulk=2 says "everything else is
        # important": an unmapped class (and None) rides the protected
        # tier.
        assert adm.admit("web") is None
        assert adm.admit(None) is None
        assert adm.protected("web") and not adm.protected("bulk")

    def test_single_tier_map_never_sheds(self):
        # One mapped level means one tier — the top tier, which policy
        # never sheds: a priority map needs a sheddable tier to act.
        adm = fresh_admission({"bulk": 2}, FakeCapacity(headroom=0.1))
        for _ in range(4):
            assert adm.admit("bulk") is None

    def test_recovery_restores_tiers(self):
        cap = FakeCapacity(headroom=0.4)
        adm = fresh_admission({"interactive": 0, "bulk": 2}, cap)
        assert isinstance(adm.admit("bulk"), ShedByPolicy)
        cap.headroom = 2.0  # well past release_headroom
        assert adm.admit("bulk") is None
        moves = adm.export()["moves"]
        assert moves == {"shed": 1, "restore": 1}

    def test_cooldown_freezes_the_cutoff(self):
        adm = fresh_admission({"interactive": 0, "batch": 1, "bulk": 2},
                              FakeCapacity(headroom=0.1),
                              cooldown_ms=3600_000.0)
        assert isinstance(adm.admit("bulk"), ShedByPolicy)
        # A second tier would need another move, frozen for an hour.
        assert adm.admit("batch") is None

    def test_audit_and_export_describe_the_cutoff(self):
        adm = fresh_admission({"interactive": 0, "bulk": 2},
                              FakeCapacity(headroom=0.5))
        adm.admit("bulk")
        ex = adm.export()
        assert ex["cutoff_priority"] == 2
        assert ex["protected_priority"] == 0
        assert ex["audit"][-1]["action"] == "shed"
        assert ex["audit"][-1]["headroom_ratio"] == 0.5

    def test_retry_after_is_bounded_and_headroom_priced(self):
        adm = fresh_admission({"interactive": 0, "bulk": 2},
                              FakeCapacity(headroom=0.1))
        adm.admit("bulk")
        for _ in range(32):
            assert (RETRY_AFTER_MIN_S <= adm.retry_after_s()
                    <= RETRY_AFTER_MAX_S)
        shed = adm.admit("bulk")
        assert RETRY_AFTER_MIN_S <= shed.retry_after_s <= RETRY_AFTER_MAX_S

    def test_no_signals_means_fully_open_forever(self):
        adm = fresh_admission({"interactive": 0, "bulk": 2}, None)
        for _ in range(4):
            assert adm.admit("bulk") is None


class TestBatcherShedIntegration:
    @pytest.fixture
    def model(self):
        rng = np.random.default_rng(7)
        x = rng.normal(0, 1, (64, 4)).astype(np.float32)
        y = rng.integers(0, 3, 64).astype(np.int32)
        return KNNClassifier(k=3).fit(Dataset(x, y))

    def test_shed_is_typed_and_ordered(self, model):
        cap = FakeCapacity(headroom=0.3)
        adm = fresh_admission({"interactive": 0, "bulk": 2}, cap)
        with MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                          admission=adm) as b:
            q = np.zeros(4, np.float32)
            with pytest.raises(ShedByPolicy) as ei:
                b.submit(q, "predict", request_class="bulk")
            assert ei.value.retry_after_s >= RETRY_AFTER_MIN_S
            # The protected class still serves THROUGH the same batcher.
            r = b.submit(q, "predict", request_class="interactive")
            assert r.result(timeout=30) is not None
            # Recovery reopens the shed tier end to end.
            cap.headroom = 2.0
            r = b.submit(q, "predict", request_class="bulk")
            assert r.result(timeout=30) is not None


class TestBrownoutLadder:
    def make(self, cap, clock, **kw):
        calls = []
        steps = [
            BrownoutStep("shadow_rate",
                         lambda: calls.append("shadow-"),
                         lambda: calls.append("shadow+")),
            BrownoutStep("probes",
                         lambda: calls.append("probes-"),
                         lambda: calls.append("probes+")),
        ]
        kw.setdefault("cooldown_ms", 1000.0)
        ctl = BrownoutController(steps, capacity=cap, autostart=False,
                                 clock=lambda: clock[0], **kw)
        return ctl, calls

    def test_applies_in_order_and_reverts_lifo(self):
        cap = FakeCapacity(headroom=0.5)
        clock = [0.0]
        ctl, calls = self.make(cap, clock)
        ctl.tick()
        assert calls == ["shadow-"] and ctl.level == 1
        clock[0] += 2.0  # past cooldown; pressure persists
        ctl.tick()
        assert calls == ["shadow-", "probes-"] and ctl.level == 2
        # Recovery reverts the LAST-applied step first.
        cap.headroom = 2.0
        clock[0] += 2.0
        ctl.tick()
        assert calls[-1] == "probes+" and ctl.level == 1
        clock[0] += 2.0
        ctl.tick()
        assert calls[-1] == "shadow+" and ctl.level == 0
        assert ctl.moves == {"apply": 2, "revert": 2}
        assert ctl.export()["applied"] == []

    def test_cooldown_bounds_walk_rate(self):
        cap = FakeCapacity(headroom=0.5)
        clock = [0.0]
        ctl, calls = self.make(cap, clock)
        ctl.tick()
        ctl.tick()  # same instant: frozen
        clock[0] += 0.5  # still inside the 1s cooldown
        ctl.tick()
        assert calls == ["shadow-"] and ctl.level == 1

    def test_failed_knob_is_audited_and_does_not_kill_the_walk(self):
        cap = FakeCapacity(headroom=0.5)
        clock = [0.0]
        boom = BrownoutStep("boom",
                            lambda: (_ for _ in ()).throw(RuntimeError("x")),
                            lambda: None)
        ok_calls = []
        ctl = BrownoutController(
            [boom, BrownoutStep("ok", lambda: ok_calls.append("-"),
                                lambda: ok_calls.append("+"))],
            capacity=cap, autostart=False, cooldown_ms=1000.0,
            clock=lambda: clock[0])
        ctl.tick()
        assert ctl.export()["audit"][-1]["action"] == "apply-failed"
        clock[0] += 2.0
        ctl.tick()
        assert ok_calls == ["-"] and ctl.level == 2

    def test_defer_background_tracks_negative_headroom(self):
        cap = FakeCapacity(headroom=0.8)
        clock = [0.0]
        ctl, _calls = self.make(cap, clock)
        assert not ctl.defer_background()  # no signal read yet
        ctl.tick()
        assert ctl.defer_background()
        cap.headroom = 1.5
        clock[0] += 2.0
        ctl.tick()
        assert not ctl.defer_background()

    def test_no_signal_rests_fully_healthy(self):
        clock = [0.0]
        ctl, calls = self.make(None, clock)
        for _ in range(3):
            ctl.tick()
            clock[0] += 2.0
        assert calls == [] and ctl.level == 0


class FakeWorkloadCapture:
    """The three calls the autotuner makes against the capture layer."""

    def start(self, reason=None, window_s=None):
        pass

    def stop(self):
        return {"path": "fake-window"}


class FakeWorkload:
    def __init__(self, n=64, spacing_ms=4.0):
        self._arrivals = [(i * spacing_ms, 1) for i in range(n)]

    def arrivals(self):
        return list(self._arrivals)


class FakeTunableBatcher:
    max_batch = 8
    buckets = None

    def __init__(self, max_wait_ms=4.0):
        self.max_wait_ms = max_wait_ms


@pytest.fixture
def tuner_parts(monkeypatch):
    import knn_tpu.obs.workload as workload_mod

    monkeypatch.setattr(workload_mod, "load_workload",
                        lambda path: FakeWorkload())
    batcher = FakeTunableBatcher(max_wait_ms=4.0)
    cap = FakeCapacity(dispatch_model={"a_ms": 1.0, "b_ms_per_row": 0.05})

    def make(replay_fn):
        t = BatchAutotuner(batcher, cap, FakeWorkloadCapture(),
                           interval_s=30.0, replay_fn=replay_fn,
                           autostart=False)
        t._stop.set()  # capture window returns instantly in tests
        return t

    return batcher, make


class TestAutotuneReplayGate:
    def test_refuses_divergent_replay_and_restores(self, tuner_parts):
        batcher, make = tuner_parts
        applied = []

        def replay(wl, batcher=None, speed=None, replay_mutations=None):
            applied.append(batcher.max_wait_ms)
            return {"verify": {"divergences": 3, "verified": 61}}

        entry = make(replay).run_cycle()
        assert entry["outcome"] == "refused"
        assert entry["replay_divergences"] == 3
        # The candidate WAS live during verification…
        assert applied and applied[0] != 4.0
        # …and was rolled back the moment replay disproved it.
        assert batcher.max_wait_ms == 4.0

    def test_refuses_unverifiable_replay_and_restores(self, tuner_parts):
        batcher, make = tuner_parts

        def replay(wl, **kw):
            raise RuntimeError("replay harness fell over")

        entry = make(replay).run_cycle()
        assert entry["outcome"] == "refused"
        assert "replay harness" in entry["error"]
        assert batcher.max_wait_ms == 4.0

    def test_applies_only_a_proven_candidate(self, tuner_parts):
        batcher, make = tuner_parts

        def replay(wl, **kw):
            return {"verify": {"divergences": 0, "verified": 64}}

        t = make(replay)
        entry = t.run_cycle()
        assert entry["outcome"] == "applied"
        assert batcher.max_wait_ms == entry["candidate_max_wait_ms"] != 4.0
        assert t.export()["outcomes"]["applied"] == 1

    def test_skips_thin_captures(self, tuner_parts, monkeypatch):
        import knn_tpu.obs.workload as workload_mod

        monkeypatch.setattr(workload_mod, "load_workload",
                            lambda path: FakeWorkload(n=5))
        batcher, make = tuner_parts
        entry = make(lambda wl, **kw: None).run_cycle()
        assert entry["outcome"] == "skipped"
        assert entry["reason"] == "too_few_requests"
        assert batcher.max_wait_ms == 4.0


class TestAutoscalePolicy:
    def make(self, clock, **kw):
        kw.setdefault("cooldown_s", 10.0)
        return AutoscalePolicy(1, 4, clock=lambda: clock[0], **kw)

    def test_no_model_no_action(self):
        clock = [100.0]
        pol = self.make(clock)
        assert pol.decide(1000.0, None, 2) is None
        assert pol.decide(1000.0, 0.0, 2) is None

    def test_up_past_the_up_fraction(self):
        clock = [100.0]
        pol = self.make(clock)
        assert pol.decide(79.0, 100.0, 2) is None
        assert pol.decide(81.0, 100.0, 2) == "up"

    def test_never_up_past_scale_max(self):
        clock = [100.0]
        pol = self.make(clock)
        assert pol.decide(999.0, 100.0, 4) is None

    def test_down_only_when_remaining_fleet_fits_it(self):
        clock = [100.0]
        pol = self.make(clock)
        # 3 replicas at ~33 qps each; offered 10 < 0.4 * 66 remaining.
        assert pol.decide(10.0, 100.0, 3) == "down"
        clock[0] += 20.0
        # Offered 30 does NOT fit under 0.4 * 66: hold.
        assert pol.decide(30.0, 100.0, 3) is None

    def test_never_down_below_scale_min(self):
        clock = [100.0]
        pol = self.make(clock)
        assert pol.decide(0.0, 50.0, 1) is None

    def test_cooldown_separates_any_two_actions(self):
        clock = [100.0]
        pol = self.make(clock)
        assert pol.decide(81.0, 100.0, 2) == "up"
        assert pol.decide(81.0, 100.0, 2) is None  # frozen
        clock[0] += 11.0
        assert pol.decide(81.0, 100.0, 2) == "up"
        assert pol.decisions == {"up": 2, "down": 0}

    def test_bounds_validated(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(0, 2)
        with pytest.raises(ValueError):
            AutoscalePolicy(3, 2)
        with pytest.raises(ValueError):
            AutoscalePolicy(1, 2, up_fraction=0.3, down_fraction=0.5)

    def test_run_scale_cmd_passes_direction_and_url(self, tmp_path):
        out = tmp_path / "scale.log"
        script = tmp_path / "scale.sh"
        script.write_text(f"#!/bin/sh\necho \"$1 $2\" >> {out}\n")
        script.chmod(0o755)
        run_scale_cmd(str(script), "up", "http://r3:8000", timeout_s=30)
        assert out.read_text().strip() == "up http://r3:8000"

    def test_run_scale_cmd_raises_on_failure(self):
        import subprocess

        with pytest.raises(subprocess.CalledProcessError):
            run_scale_cmd("false", "down", "http://r1:8000", timeout_s=30)


class TestShedSLOExclusion:
    def test_policy_sheds_spend_no_availability_budget(self):
        slo = SLOTracker(windows_s=(60,))
        for _ in range(20):
            slo.record(ok=True, latency_ms=1.0)
        for _ in range(50):
            slo.record_shed()
        burns = slo.burn_rates()
        assert burns["availability"]["1m"] == 0.0
        ex = slo.export()
        assert ex["policy_sheds"]["1m"] == 50

    def test_protected_429s_still_burn(self):
        # The contrast case: a non-shed overload rejection IS recorded
        # as a failed request and burns availability.
        slo = SLOTracker(windows_s=(60,))
        for _ in range(10):
            slo.record(ok=False, latency_ms=1.0)
        assert slo.burn_rates()["availability"]["1m"] > 0.0


class TestDegradationOrderContract:
    def test_order_is_scale_shed_brownout_availability(self):
        assert DEGRADATION_ORDER == (
            "scale", "shed_low_priority", "brownout_quality",
            "availability")


class TestServeAppWiring:
    @pytest.fixture
    def model(self):
        rng = np.random.default_rng(11)
        x = rng.normal(0, 1, (64, 4)).astype(np.float32)
        y = rng.integers(0, 3, 64).astype(np.int32)
        return KNNClassifier(k=3).fit(Dataset(x, y))

    def test_priority_requires_cost_accounting(self, model):
        from knn_tpu.serve.server import ServeApp

        with pytest.raises(DataError, match="cost-accounting"):
            ServeApp(model, max_batch=8, max_wait_ms=0.0,
                     priority_map={"bulk": 2})

    def test_brownout_requires_a_knob(self, model):
        from knn_tpu.serve.server import ServeApp

        # Flagless serve has no reversible knob wired: shadow/drift off,
        # no ivf policy, no deadline — --brownout must refuse, not spin
        # an empty ladder.
        with pytest.raises(DataError, match="reversible knob"):
            ServeApp(model, max_batch=8, max_wait_ms=0.0, brownout=True)

    def test_autotune_requires_capture_and_accounting(self, model):
        from knn_tpu.serve.server import ServeApp

        with pytest.raises(DataError):
            ServeApp(model, max_batch=8, max_wait_ms=0.0,
                     autotune_interval_s=30.0)

    def test_control_block_and_threads_wired_when_flagged(self, model,
                                                          tmp_path):
        from knn_tpu.serve.server import ServeApp

        app = ServeApp(model, max_batch=8, max_wait_ms=0.0,
                       cost_accounting=True, shadow_rate=0.1,
                       capture_dir=str(tmp_path),
                       priority_map={"interactive": 0, "bulk": 2},
                       brownout=True, autotune_interval_s=3600.0)
        try:
            block = app.control_block()
            assert block["admission"]["priority_map"] == {
                "interactive": 0, "bulk": 2}
            assert "shadow_rate" in block["brownout"]["steps"]
            assert block["autotune"]["interval_s"] == 3600.0
            names = {t.name for t in threading.enumerate()}
            assert "knn-control-brownout" in names
            assert "knn-control-autotune" in names
            assert app.batcher.admission is app.admission
        finally:
            app.close()
        alive = {t.name for t in threading.enumerate()
                 if t.is_alive() and t.name.startswith("knn-control")}
        assert not alive
