"""The IVF index family (knn_tpu/index/, docs/INDEXES.md): k-means
partition build, the shared (distance, index) tie-order contract pinned
across every rung, probed search with the bit-identity and
never-return-short guarantees, degenerate partitions through
save/load/serve, the burn-aware probe policy, and the serving ladder's
ivf rung."""

from __future__ import annotations

import json

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.backends.oracle import oracle_kneighbors
from knn_tpu.data.dataset import Dataset
from knn_tpu.index.ivf import IVFIndex, IVFServing
from knn_tpu.index.kmeans import kmeans
from knn_tpu.index.probe_policy import ProbePolicy
from knn_tpu.models.knn import KNNClassifier, _kneighbors_arrays
from knn_tpu.models.ordering import lexicographic_topk
from knn_tpu.resilience.errors import DataError
from knn_tpu.serve import artifact
from knn_tpu.serve.batcher import MicroBatcher
from knn_tpu.serve.server import ServeApp


def _data(rng, n=1200, q=60, d=7):
    """Clustered rows with duplicated blocks (dist==0 ties) and a few
    exact-hit queries — the adversarial tie surface."""
    centers = rng.normal(0, 5, (8, d))
    x = (centers[rng.integers(0, 8, n)]
         + rng.normal(0, 1.0, (n, d))).astype(np.float32)
    dup = min(50, n // 3)  # duplicate rows: dist==0 and tie-order pressure
    x[dup:2 * dup] = x[:dup]
    qx = (centers[rng.integers(0, 8, q)]
          + rng.normal(0, 1.0, (q, d))).astype(np.float32)
    hits = min(10, q)
    qx[:hits] = x[rng.integers(0, n, hits)]  # exact hits
    return x, qx


class TestKMeans:
    def test_deterministic_and_covering(self, rng):
        x, _ = _data(rng)
        c1, a1, info1 = kmeans(x, 16, seed=3)
        c2, a2, info2 = kmeans(x, 16, seed=3)
        assert np.array_equal(c1, c2) and np.array_equal(a1, a2)
        assert info1 == info2
        assert a1.min() >= 0 and a1.max() < 16
        assert c1.shape == (16, x.shape[1]) and c1.dtype == np.float32

    def test_seed_changes_partition(self, rng):
        x, _ = _data(rng)
        _, a1, _ = kmeans(x, 16, seed=0)
        _, a2, _ = kmeans(x, 16, seed=99)
        assert not np.array_equal(a1, a2)

    def test_duplicate_heavy_data_allows_empty_cells(self):
        # 3 distinct points, 8 cells: the empty-cell repair saturates and
        # the residual cells stay (validly) empty.
        x = np.repeat(np.arange(3, dtype=np.float32)[:, None], 10,
                      axis=0).repeat(2, axis=1)
        cents, assign, info = kmeans(x, 8, seed=0)
        assert info["empty_cells"] >= 5
        assert np.bincount(assign, minlength=8).sum() == x.shape[0]

    def test_validation(self, rng):
        x, _ = _data(rng, n=50)
        with pytest.raises(ValueError):
            kmeans(x, 0)
        with pytest.raises(ValueError):
            kmeans(x, 51)
        with pytest.raises(ValueError):
            kmeans(x, 4, iters=0)


class TestTieOrderHelper:
    def test_matches_naive_lexsort_on_heavy_ties(self, rng):
        d = rng.integers(0, 3, (40, 120)).astype(np.float32)
        idx = rng.permutation(120)
        got_d, got_i = lexicographic_topk(d, idx, 9)
        for row in range(40):
            order = np.lexsort((idx, d[row]))[:9]
            assert np.array_equal(got_i[row], idx[order])
            assert np.array_equal(got_d[row], d[row][order])

    def test_packed_equals_fallback(self, rng):
        # float32 rides the packed-key path; float64 the lexsort loop —
        # the two realizations of the ONE contract must agree exactly.
        d = rng.integers(0, 4, (30, 200)).astype(np.float32)
        d[:, ::7] = np.inf  # inf ties too
        idx = np.arange(200)
        pd, pi = lexicographic_topk(d, idx, 11)
        fd, fi = lexicographic_topk(d.astype(np.float64), idx, 11)
        assert np.array_equal(pi, fi)
        assert np.array_equal(pd, fd.astype(np.float32))

    def test_per_row_indices_and_validation(self, rng):
        d = rng.random((4, 10)).astype(np.float32)
        idx = np.tile(np.arange(10), (4, 1))
        a = lexicographic_topk(d, idx, 3)
        b = lexicographic_topk(d, np.arange(10), 3)
        assert np.array_equal(a[1], b[1])
        with pytest.raises(ValueError):
            lexicographic_topk(d, np.arange(9), 3)
        with pytest.raises(ValueError):
            lexicographic_topk(d[0], np.arange(10), 3)


class TestTieOrderEveryRung:
    """The satellite pin: EVERY rung — oracle, xla, auto(fast), and the
    ivf path at full probe — reproduces the shared helper's order on
    tie-heavy data."""

    def test_all_rungs_match_helper(self, rng):
        x, qx = _data(rng, n=400, q=25)
        k = 7
        from knn_tpu.backends.oracle import _metric_dists

        full = _metric_dists(qx, x, "euclidean")
        np.nan_to_num(full, copy=False, nan=np.inf)
        want_d, want_i = lexicographic_topk(full, np.arange(x.shape[0]), k)

        got_d, got_i = oracle_kneighbors(x, qx, k)
        assert np.array_equal(got_i, want_i), "oracle diverged"
        assert np.array_equal(got_d, want_d)

        for engine in ("xla", "auto"):
            got_d, got_i = _kneighbors_arrays(x, qx, k, engine=engine)
            assert np.array_equal(np.asarray(got_i, np.int64), want_i), \
                f"engine {engine} diverged from the tie-order contract"
            np.testing.assert_allclose(got_d, want_d, rtol=1e-5)

        ivf = IVFIndex.build(x, 8, seed=0)
        got_d, got_i = ivf.search(x, qx, k, 8)[:2]
        assert np.array_equal(got_i, want_i), "ivf full probe diverged"
        assert np.array_equal(got_d, want_d)


class TestIVFSearch:
    def test_full_probe_bit_identical_to_exact(self, rng):
        x, qx = _data(rng)
        qx[5] = np.nan  # NaN query row follows the NaN -> +inf policy
        ivf = IVFIndex.build(x, 24, seed=0)
        od, oi = oracle_kneighbors(x, qx, 5)
        d, i, st = ivf.search(x, qx, 5, 24)
        assert np.array_equal(d, od) and np.array_equal(i, oi)
        assert st.nprobe == 24 and st.candidate_rows == qx.shape[0] * 1200

    def test_recall_monotone_to_one(self, rng):
        x, qx = _data(rng)
        ivf = IVFIndex.build(x, 24, seed=0)
        od, oi = oracle_kneighbors(x, qx, 5)
        from knn_tpu.obs.quality import recall_at_k

        r1 = recall_at_k(ivf.search(x, qx, 5, 1)[1], oi,
                         od.astype(np.float64),
                         ivf.search(x, qx, 5, 1)[0].astype(
                             np.float64)).mean()
        r24 = recall_at_k(ivf.search(x, qx, 5, 24)[1], oi,
                          od.astype(np.float64),
                          ivf.search(x, qx, 5, 24)[0].astype(
                              np.float64)).mean()
        assert r24 == 1.0 and r1 <= r24

    def test_k_over_probed_candidates_widens_never_short(self, rng):
        # 32 cells over 64 rows: ~2 rows per cell; k=10 forces widening.
        x, qx = _data(rng, n=64, q=8)
        ivf = IVFIndex.build(x, 32, seed=1)
        d, i, st = ivf.search(x, qx, 10, 1)
        assert i.shape == (8, 10)
        assert st.forced_widenings > 0 and st.nprobe > st.requested
        assert (i < 64).all(), "a pad sentinel leaked into results"
        # and the widened result is still tie-contract-correct
        od, oi = oracle_kneighbors(x, qx, 10)
        from knn_tpu.obs.quality import recall_at_k

        r = recall_at_k(i, oi, od.astype(np.float64),
                        d.astype(np.float64)).mean()
        assert r > 0.5

    def test_empty_cells_serve(self):
        # Duplicate-heavy data leaves cells empty; search must still
        # answer exactly at full probe and never return short.
        x = np.repeat(np.arange(4, dtype=np.float32)[:, None], 8,
                      axis=0).repeat(3, axis=1)
        ivf = IVFIndex.build(x, 16, seed=0)
        assert int((ivf.cell_sizes == 0).sum()) > 0
        qx = x[:5] + 0.1
        od, oi = oracle_kneighbors(x, qx, 6)
        d, i, st = ivf.search(x, qx, 6, 16)
        assert np.array_equal(d, od) and np.array_equal(i, oi)

    def test_single_cell_is_exact(self, rng):
        x, qx = _data(rng, n=300, q=20)
        ivf = IVFIndex.build(x, 1, seed=0)
        od, oi = oracle_kneighbors(x, qx, 5)
        d, i, _ = ivf.search(x, qx, 5, 1)
        assert np.array_equal(d, od) and np.array_equal(i, oi)

    def test_k_clamps_to_n_and_nprobe_to_cells(self, rng):
        x, qx = _data(rng, n=40, q=4)
        ivf = IVFIndex.build(x, 4, seed=0)
        d, i, st = ivf.search(x, qx, 100, 99)
        assert i.shape == (4, 40) and st.nprobe == 4
        od, oi = oracle_kneighbors(x, qx, 100)
        assert np.array_equal(i, oi)

    def test_row_count_mismatch_typed(self, rng):
        x, qx = _data(rng, n=100, q=4)
        ivf = IVFIndex.build(x, 4, seed=0)
        with pytest.raises(DataError):
            ivf.search(x[:50], qx, 3, 2)


def _save_ivf_index(tmp_path, x, cells=8, k=3, name="idx"):
    train = Dataset(x, np.zeros(x.shape[0], np.int32))
    model = KNNClassifier(k=k).fit(train)
    ivf = IVFIndex.build(x, cells, seed=0)
    out = artifact.save_index(model, tmp_path / name, ivf=ivf)
    return out, model, ivf


class TestIVFArtifact:
    def test_round_trip(self, rng, tmp_path):
        x, qx = _data(rng, n=200, q=10)
        out, model, ivf = _save_ivf_index(tmp_path, x)
        manifest = artifact.read_manifest(out)
        assert manifest["format"] == 3
        assert manifest["ivf"]["num_cells"] == 8
        assert manifest["ivf"]["seed"] == 0
        loaded = artifact.load_index(out)
        got = loaded.ivf_
        assert np.array_equal(got.centroids, ivf.centroids)
        assert np.array_equal(got.row_perm, ivf.row_perm)
        assert np.array_equal(got.cell_offsets, ivf.cell_offsets)
        # and re-saving a LOADED model keeps the partition (ivf rides
        # model.ivf_ through save_index's default)
        out2 = artifact.save_index(loaded, tmp_path / "resave")
        assert artifact.read_manifest(out2)["ivf"]["num_cells"] == 8

    def test_exact_only_artifact_has_no_partition(self, rng, tmp_path):
        x, _ = _data(rng, n=200, q=10)
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        model = KNNClassifier(k=3).fit(train)
        out = artifact.save_index(model, tmp_path / "plain")
        loaded = artifact.load_index(out)
        assert getattr(loaded, "ivf_", None) is None

    def test_format2_artifact_loads_and_serves_exact_only(
            self, rng, tmp_path):
        # A pre-IVF artifact: rewrite the manifest to format 2 (no ivf
        # block). It must load, carry no partition, and serve exact.
        x, qx = _data(rng, n=200, q=10)
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        model = KNNClassifier(k=3).fit(train)
        out = artifact.save_index(model, tmp_path / "fmt2")
        mf = json.loads((out / "manifest.json").read_text())
        assert "ivf" not in mf
        mf["format"] = 2
        (out / "manifest.json").write_text(json.dumps(mf))
        loaded = artifact.load_index(out)
        assert getattr(loaded, "ivf_", None) is None
        app = ServeApp(loaded, max_batch=8, max_wait_ms=0.0)
        try:
            assert app.ivf is None and app.primary_rung == "fast"
            h = app.batcher.submit(qx[:2], "kneighbors")
            d, i = h.result(timeout=30)
            od, oi = oracle_kneighbors(x, qx[:2], 3)
            assert np.array_equal(i, oi)
        finally:
            app.close()

    def test_ivf_probes_on_exact_only_artifact_typed(self, rng, tmp_path):
        x, _ = _data(rng, n=200, q=10)
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        model = KNNClassifier(k=3).fit(train)
        with pytest.raises(DataError):
            ServeApp(model, ivf_probes=4)

    def test_corrupt_partition_typed_at_load(self, rng, tmp_path):
        x, _ = _data(rng, n=200, q=10)
        out, _, ivf = _save_ivf_index(tmp_path, x, name="corrupt")
        arrays = dict(np.load(out / "arrays.npz", allow_pickle=False))
        arrays["ivf_row_perm"] = np.zeros_like(arrays["ivf_row_perm"])
        np.savez(out / "arrays.npz", **arrays)
        with pytest.raises(DataError, match="permutation"):
            artifact.load_index(out)

    def test_manifest_arrays_cell_count_mismatch_typed(
            self, rng, tmp_path):
        x, _ = _data(rng, n=200, q=10)
        out, _, _ = _save_ivf_index(tmp_path, x, name="mismatch")
        mf = json.loads((out / "manifest.json").read_text())
        mf["ivf"]["num_cells"] = 99
        (out / "manifest.json").write_text(json.dumps(mf))
        with pytest.raises(DataError, match="num_cells"):
            artifact.load_index(out)

    def test_stale_partition_rejected_at_save(self, rng, tmp_path):
        x, _ = _data(rng, n=200, q=10)
        other = rng.normal(0, 1, (50, 7)).astype(np.float32)
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        model = KNNClassifier(k=3).fit(train)
        with pytest.raises(ValueError, match="rebuild"):
            artifact.save_index(model, tmp_path / "stale",
                                ivf=IVFIndex.build(other, 4, seed=0))

    def test_non_euclidean_partition_rejected_at_save(self, rng, tmp_path):
        # The cells are squared-euclidean Voronoi regions; pairing them
        # with any other metric would rank cells by the wrong geometry.
        x, _ = _data(rng, n=150, q=5)
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        model = KNNClassifier(k=3, metric="manhattan").fit(train)
        with pytest.raises(ValueError, match="euclidean-only"):
            artifact.save_index(model, tmp_path / "manh",
                                ivf=IVFIndex.build(x, 4, seed=0))

    def test_hand_edited_metric_with_partition_typed_at_load(
            self, rng, tmp_path):
        # schema_hash covers attribute metadata, not the metric field —
        # an edited manifest must fail typed at load, never serve
        # wrong-geometry answers.
        x, _ = _data(rng, n=150, q=5)
        out, _, _ = _save_ivf_index(tmp_path, x, name="edited")
        mf = json.loads((out / "manifest.json").read_text())
        mf["metric"] = "manhattan"
        (out / "manifest.json").write_text(json.dumps(mf))
        with pytest.raises(DataError, match="euclidean-only"):
            artifact.load_index(out)

    def test_degenerate_partitions_round_trip_serve(self, rng, tmp_path):
        # single-cell AND empty-cell partitions survive
        # save -> load -> serve with full-probe exactness.
        dup = np.repeat(np.arange(4, dtype=np.float32)[:, None], 8,
                        axis=0).repeat(3, axis=1)
        cases = {
            "single": (_data(rng, n=150, q=6)[0], 1),
            "empties": (dup, 16),
        }
        for name, (x, cells) in cases.items():
            out, model, _ = _save_ivf_index(tmp_path, x, cells=cells,
                                            name=name)
            loaded = artifact.load_index(out)
            app = ServeApp(loaded, max_batch=8, max_wait_ms=0.0,
                           ivf_probes=cells)
            try:
                qx = x[:4] + 0.05
                h = app.batcher.submit(qx, "kneighbors")
                d, i = h.result(timeout=30)
                assert h.meta["rung"] == "ivf"
                od, oi = oracle_kneighbors(x, qx, 3)
                assert np.array_equal(d, od) and np.array_equal(i, oi)
            finally:
                app.close()


class _FakeSLO:
    """Programmable quality-burn source for policy tests."""

    def __init__(self, burn=0.0):
        self.burn = burn
        self.windows_s = (5, 60)

    def burn_rates(self):
        return {"quality": {"5s": self.burn, "1m": self.burn / 2}}


class TestProbePolicy:
    def _policy(self, slo, **kw):
        kw.setdefault("cooldown_ms", 0.0)
        kw.setdefault("eval_ms", 0.0)
        return ProbePolicy(4, 32, slo=slo, **kw)

    def test_static_without_signal(self):
        p = ProbePolicy(4, 32, slo=None)
        assert p.current() == 4

    def test_widens_under_burn_doubling_to_ceiling(self):
        slo = _FakeSLO(burn=5.0)
        p = self._policy(slo)
        seen = [p.current() for _ in range(5)]
        assert seen == [8, 16, 32, 32, 32]

    def test_narrows_back_to_base_when_healthy(self):
        slo = _FakeSLO(burn=5.0)
        p = self._policy(slo)
        for _ in range(4):
            p.current()
        slo.burn = 0.0
        seen = [p.current() for _ in range(5)]
        assert seen == [16, 8, 4, 4, 4]
        assert p.moves == {"widen": 3, "narrow": 3}

    def test_hysteresis_band_holds(self):
        # Between narrow_burn and widen_burn: no move in either direction.
        slo = _FakeSLO(burn=0.6)
        p = self._policy(slo)
        assert [p.current() for _ in range(3)] == [4, 4, 4]

    def test_cooldown_freezes_moves(self):
        slo = _FakeSLO(burn=5.0)
        p = ProbePolicy(4, 32, slo=slo, cooldown_ms=60000.0, eval_ms=0.0)
        assert p.current() == 8  # first move
        assert p.current() == 8  # frozen by cooldown
        assert p.moves["widen"] == 1

    def test_eval_interval_caches(self):
        slo = _FakeSLO(burn=5.0)
        p = ProbePolicy(4, 32, slo=slo, cooldown_ms=0.0, eval_ms=60000.0)
        assert p.current() == 8
        assert p.current() == 8  # cached, no re-eval

    def test_broken_signal_reads_zero_not_crash(self):
        class Broken:
            windows_s = (5,)

            def burn_rates(self):
                raise RuntimeError("scrape exploded")

        p = self._policy(Broken())
        assert p.current() == 4

    def test_reload_rebound(self):
        p = self._policy(_FakeSLO(burn=5.0))
        for _ in range(3):
            p.current()
        assert p.current() == 32
        p.set_num_cells(8)
        assert p.export()["nprobe"] == 8
        assert p.export()["max_probes"] == 8

    def test_reload_round_trip_restores_configured_base(self):
        # small-index reload clamps base down; reloading the original
        # index back must restore the operator's configured operating
        # point — never a one-way ratchet.
        p = ProbePolicy(8, 128, slo=None)
        p.set_num_cells(4)
        assert p.export()["base_probes"] == 4
        assert p.current() == 4
        p.set_num_cells(128)
        assert p.export()["base_probes"] == 8
        assert p.current() == 8

    def test_validation(self):
        with pytest.raises(ValueError):
            ProbePolicy(0, 8)
        with pytest.raises(ValueError):
            ProbePolicy(9, 8)
        with pytest.raises(ValueError):
            ProbePolicy(2, 8, widen_burn=0.5, narrow_burn=1.0)


class TestServingLadder:
    def test_ivf_rung_answers_and_tags_meta(self, rng):
        x, qx = _data(rng, n=400, q=8)
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        model = KNNClassifier(k=3).fit(train)
        setattr(model, "ivf_", IVFIndex.build(x, 8, seed=0))
        serving = IVFServing(4, 8)
        with MicroBatcher(model, max_batch=16, max_wait_ms=0.0,
                          ivf=serving) as b:
            h = b.submit(qx[:3], "kneighbors")
            d, i = h.result(timeout=30)
            assert h.meta["rung"] == "ivf"
            want = model.ivf_.search(x, qx[:3], 3, 4)
            assert np.array_equal(d, want[0])
            assert np.array_equal(i, want[1])
            # predict requests vote from the ivf candidates
            h2 = b.submit(qx[:3], "predict")
            preds = h2.result(timeout=30)
            assert h2.meta["rung"] == "ivf"
            assert preds.shape == (3,)

    def test_ivf_failure_degrades_to_exact(self, rng, monkeypatch):
        x, qx = _data(rng, n=400, q=8)
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        model = KNNClassifier(k=3).fit(train)
        setattr(model, "ivf_", IVFIndex.build(x, 8, seed=0))
        serving = IVFServing(4, 8)
        from knn_tpu.resilience.errors import DeviceError

        def boom(model_, feats):
            raise DeviceError("ivf rung down")

        monkeypatch.setattr(serving, "kneighbors", boom)
        with MicroBatcher(model, max_batch=16, max_wait_ms=0.0,
                          ivf=serving) as b:
            h = b.submit(qx[:3], "kneighbors")
            d, i = h.result(timeout=30)
            # fell to an EXACT rung: bit-identical to the oracle contract
            od, oi = oracle_kneighbors(x, qx[:3], 3)
            assert h.meta["rung"] != "ivf"
            assert np.array_equal(np.asarray(i, np.int64), oi)

    def test_ivf_data_error_degrades_to_exact(self, rng, monkeypatch):
        # The ivf rung degrades on the WHOLE typed taxonomy, not just
        # device errors: a DataError (index/model desync) trades
        # approximation away for bit-exact retrieval, never a failed
        # batch.
        x, qx = _data(rng, n=400, q=8)
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        model = KNNClassifier(k=3).fit(train)
        setattr(model, "ivf_", IVFIndex.build(x, 8, seed=0))
        serving = IVFServing(4, 8)

        def boom(model_, feats):
            raise DataError("index spans 0 rows but the train set has 400")

        monkeypatch.setattr(serving, "kneighbors", boom)
        with MicroBatcher(model, max_batch=16, max_wait_ms=0.0,
                          ivf=serving) as b:
            h = b.submit(qx[:3], "kneighbors")
            d, i = h.result(timeout=30)
            od, oi = oracle_kneighbors(x, qx[:3], 3)
            assert h.meta["rung"] != "ivf"
            assert np.array_equal(np.asarray(i, np.int64), oi)

    def test_without_serving_wrapper_no_ivf_rung(self, rng):
        x, qx = _data(rng, n=200, q=4)
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        model = KNNClassifier(k=3).fit(train)
        setattr(model, "ivf_", IVFIndex.build(x, 8, seed=0))
        with MicroBatcher(model, max_batch=8, max_wait_ms=0.0) as b:
            assert [n for n, _ in b._rungs(model)][0] == "fast"
            h = b.submit(qx[:2], "kneighbors")
            h.result(timeout=30)
            assert h.meta["rung"] == "fast"


class TestQualityFloor:
    def test_approx_floor_gates_the_sli(self, rng):
        from knn_tpu.obs.quality import ShadowScorer

        x, qx = _data(rng, n=300, q=4)
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        model = KNNClassifier(k=4).fit(train)
        verdicts = []

        class SpySLO:
            def record_quality(self, good):
                verdicts.append(good)

        scorer = ShadowScorer(1.0, seed=0, slo=SpySLO(),
                              approx_floors={"ivf": 0.75},
                              autostart=False)
        ivf = IVFIndex.build(x, 8, seed=0)
        od, oi = oracle_kneighbors(x, qx, 4)

        def offer(rung, d, i):
            assert scorer.offer(features=qx, kind="kneighbors", dists=d,
                                idx=i, preds=None, rung=rung, model=model,
                                version="v1")
            scorer._sq.start()
            assert scorer.drain(30)

        # full probe: recall 1.0 >= floor -> good
        d, i, _ = ivf.search(x, qx, 4, 8)
        offer("ivf", d, i)
        assert verdicts[-1] is True
        # an artificially bad list (k wrong neighbors) on the ivf rung:
        # mean recall under the floor -> bad, and on an exact rung any
        # divergence at all -> bad
        far = np.argsort(((x[None] - qx[:, None]) ** 2).sum(-1),
                         axis=1)[:, -4:]
        far_d = np.take_along_axis(
            ((x[None] - qx[:, None]) ** 2).sum(-1), far, axis=1)
        offer("ivf", far_d.astype(np.float32), far)
        assert verdicts[-1] is False
        summary = scorer.export()
        assert summary["approx_floors"] == {"ivf": 0.75}
        assert summary["rungs"]["ivf"]["divergence"].get("neighbors")

    def test_floor_validation(self):
        from knn_tpu.obs.quality import ShadowScorer

        with pytest.raises(ValueError):
            ShadowScorer(1.0, approx_floors={"ivf": 1.5}, autostart=False)


class TestServeAppIVF:
    def test_healthz_block_and_primary_rung(self, rng, tmp_path):
        x, qx = _data(rng, n=300, q=6)
        out, _, _ = _save_ivf_index(tmp_path, x, cells=8, name="hz")
        model = artifact.load_index(out)
        app = ServeApp(model, max_batch=8, max_wait_ms=0.0, ivf_probes=2,
                       shadow_rate=1.0, quality_seed=0)
        try:
            assert app.primary_rung == "ivf"
            assert app.quality.approx_floors == {"ivf": 0.95}
            h = app.health()
            assert h["ivf"]["num_cells"] == 8
            assert h["ivf"]["nprobe"] == 2
            assert h["ivf"]["recall_floor"] == 0.95
            fut = app.batcher.submit(qx[:2], "predict")
            fut.result(timeout=30)
        finally:
            app.close()

    def test_probes_out_of_range_typed(self, rng, tmp_path):
        x, _ = _data(rng, n=300, q=6)
        out, _, _ = _save_ivf_index(tmp_path, x, cells=8, name="range")
        model = artifact.load_index(out)
        with pytest.raises(DataError, match="out of range"):
            ServeApp(model, ivf_probes=9)

    def test_reload_to_partitionless_artifact_rolls_back(
            self, rng, tmp_path):
        x, _ = _data(rng, n=300, q=6)
        out, _, _ = _save_ivf_index(tmp_path, x, cells=8, name="a")
        train = Dataset(x, np.zeros(x.shape[0], np.int32))
        plain = artifact.save_index(
            KNNClassifier(k=3).fit(train), tmp_path / "plain")
        model = artifact.load_index(out)
        app = ServeApp(model, max_batch=8, max_wait_ms=0.0, ivf_probes=4,
                       index_path=str(out))
        try:
            app.warm((1,))
            before = app.index_version
            with pytest.raises(DataError, match="no IVF partition"):
                app.reload(str(plain))
            assert app.index_version == before  # old index still serving
            h = app.batcher.submit(x[:2], "kneighbors")
            h.result(timeout=30)
            assert h.meta["rung"] == "ivf"
        finally:
            app.close()


class TestCLI:
    def test_save_index_ivf_flags(self, small_paths, tmp_path, capsys):
        from knn_tpu import cli

        train_arff, _ = small_paths
        out = tmp_path / "idx"
        rc = cli.run(["save-index", train_arff, str(out), "--k", "3",
                      "--ivf-cells", "16"])
        assert rc == 0
        assert "ivf_cells=16" in capsys.readouterr().out
        assert artifact.read_manifest(out)["ivf"]["num_cells"] == 16

    def test_save_index_ivf_rejections(self, small_paths, tmp_path):
        from knn_tpu import cli

        train_arff, _ = small_paths
        out = str(tmp_path / "idx")
        assert cli.run(["save-index", train_arff, out,
                        "--ivf-cells", "0"]) == 2
        assert cli.run(["save-index", train_arff, out, "--ivf-cells", "4",
                        "--metric", "cosine"]) == 2
        assert cli.run(["save-index", train_arff, out, "--ivf-cells", "4",
                        "--ivf-iters", "0"]) == 2
        assert cli.run(["save-index", train_arff, out,
                        "--ivf-cells", "99999999"]) == 2

    def test_serve_ivf_flag_rejections(self, small_paths, tmp_path):
        from knn_tpu import cli

        train_arff, _ = small_paths
        idx = str(tmp_path / "idx")
        assert cli.run(["save-index", train_arff, idx, "--k", "3"]) == 0
        # bad values fail before any load
        assert cli.run(["serve", idx, "--ivf-probes", "0"]) == 2
        assert cli.run(["serve", idx, "--ivf-recall-floor", "1.5"]) == 2
        # probes against an exact-only artifact: typed, exit 2, no serve
        assert cli.run(["serve", idx, "--ivf-probes", "4"]) == 2


class TestInstruments:
    def test_ivf_rung_records_knn_ivf_metrics(self, rng, obs_on=None):
        obs.enable()
        obs.reset()
        try:
            x, qx = _data(rng, n=300, q=4)
            train = Dataset(x, np.zeros(x.shape[0], np.int32))
            model = KNNClassifier(k=3).fit(train)
            setattr(model, "ivf_", IVFIndex.build(x, 8, seed=0))
            serving = IVFServing(2, 8)
            with MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                              ivf=serving) as b:
                b.submit(qx[:2], "kneighbors").result(timeout=30)
            names = {i.name for i in obs.registry().instruments()}
            assert "knn_ivf_probes" in names
            assert "knn_ivf_queries_total" in names
            assert "knn_ivf_candidate_rows_total" in names
            assert "knn_ivf_cell_imbalance" in names
        finally:
            obs.reset()
            obs.disable()
