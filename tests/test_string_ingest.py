"""STRING/DATE data-cell ingest (VERDICT r1 #2).

The reference's instance reader accepts string-valued tokens in data rows
(libarff/arff_parser.cpp:145-147, string ctor arff_value.cpp:33-48) and only
fails when the KNN kernel reads one as float (arff_value.cpp:121 —
"operator float cannot work on type 'STRING'!"). So a file with STRING/DATE
columns must LOAD here too: cells intern to first-seen float32 codes with the
table on ``Attribute.string_values``, and the numeric-only requirement is
deferred to ``Dataset.validate_for_knn``.
"""

import math

import numpy as np
import pytest

from knn_tpu.data import pyarff
from knn_tpu.data.arff import load_arff, write_arff
from knn_tpu.data.dataset import Dataset

STRING_FILE = """@relation logs
@attribute host STRING
@attribute latency NUMERIC
@attribute when DATE
@attribute class NUMERIC
@data
web1,1.5,2021-01-01,0
web2,2.5,2021-01-02,1
web1,3.5,2021-01-01,0
'web 3',4.5,2021-01-03,1
"""


@pytest.fixture()
def native_arff():
    return pytest.importorskip(
        "knn_tpu.native.arff_native",
        reason="native arff lib not built (run `make native`)",
    )


def parse_py(text: str):
    return pyarff.parse_arff_lines(text.splitlines(), path="<test>")


class TestStringIngest:
    def test_string_cells_intern_first_seen(self):
        ds = parse_py(STRING_FILE)
        # host codes: web1=0, web2=1, 'web 3'=2 (first-seen order).
        np.testing.assert_array_equal(ds.features[:, 0], [0, 1, 0, 2])
        assert ds.attributes[0].string_values == ["web1", "web2", "web 3"]
        # date codes likewise.
        np.testing.assert_array_equal(ds.features[:, 2], [0, 1, 0, 2])
        assert ds.attributes[2].string_values == [
            "2021-01-01", "2021-01-02", "2021-01-03",
        ]
        # numeric column untouched.
        np.testing.assert_array_equal(ds.features[:, 1], [1.5, 2.5, 3.5, 4.5])
        np.testing.assert_array_equal(ds.labels, [0, 1, 0, 1])

    def test_missing_string_cell_is_nan(self):
        ds = parse_py(
            "@relation r\n@attribute s STRING\n@attribute class NUMERIC\n"
            "@data\n?,0\nx,1\n"
        )
        assert math.isnan(ds.features[0, 0])
        assert ds.features[1, 0] == 0.0
        assert ds.attributes[0].string_values == ["x"]

    def test_string_class_column_classifies_by_code(self):
        # Framework extension: interned codes are well-defined class ids
        # (the reference aborts on the label cast, main.cpp:57).
        ds = parse_py(
            "@relation r\n@attribute x NUMERIC\n@attribute label STRING\n"
            "@data\n1,cat\n2,dog\n3,cat\n"
        )
        np.testing.assert_array_equal(ds.labels, [0, 1, 0])
        assert ds.num_classes == 2
        assert ds.attributes[1].string_values == ["cat", "dog"]
        ds.validate_for_knn(1)  # string CLASS is fine; features are numeric

    def test_predict_rejects_string_features(self):
        ds = parse_py(STRING_FILE)
        with pytest.raises(ValueError, match="'host' of type string"):
            ds.validate_for_knn(1)

    def test_predict_rejects_date_features(self):
        ds = parse_py(
            "@relation r\n@attribute d DATE\n@attribute class NUMERIC\n"
            "@data\n2020-01-01,0\n"
        )
        with pytest.raises(ValueError, match="'d' of type date"):
            ds.validate_for_knn(1)

    def test_cli_clean_error_on_string_features(self, tmp_path, capsys):
        from knn_tpu.cli import run

        p = tmp_path / "s.arff"
        p.write_text(STRING_FILE)
        # Non-numeric feature columns are an input-validation rejection:
        # the usage exit code (2) under the resilience exit-code contract.
        assert run([str(p), str(p), "1", "--backend", "oracle"]) == 2
        err = capsys.readouterr().err
        assert "error:" in err and "host" in err

    def test_native_parser_parity(self, native_arff, tmp_path):
        p = tmp_path / "s.arff"
        p.write_text(STRING_FILE)
        nat = native_arff.parse(str(p))
        py = pyarff.parse_arff_file(str(p))
        np.testing.assert_array_equal(nat.features, py.features)
        np.testing.assert_array_equal(nat.labels, py.labels)
        assert [a.string_values for a in nat.attributes] == [
            a.string_values for a in py.attributes
        ]

    def test_write_arff_roundtrip(self, tmp_path):
        ds = parse_py(STRING_FILE)
        out = tmp_path / "rt.arff"
        write_arff(ds, str(out))
        back = load_arff(str(out), use_native=False)
        np.testing.assert_array_equal(back.features, ds.features)
        np.testing.assert_array_equal(back.labels, ds.labels)
        assert [a.string_values for a in back.attributes] == [
            a.string_values for a in ds.attributes
        ]

    def test_write_arff_roundtrips_apostrophes(self, tmp_path):
        # Neither parser dialect has backslash escapes; the writer must pick
        # the other quote char for values containing one.
        ds = parse_py(
            '@relation r\n@attribute who STRING\n@attribute class NUMERIC\n'
            '@data\n"O\'Brien",0\nplain,1\n'
        )
        assert ds.attributes[0].string_values == ["O'Brien", "plain"]
        out = tmp_path / "apos.arff"
        write_arff(ds, str(out))
        back = load_arff(str(out), use_native=False)
        assert back.attributes[0].string_values == ["O'Brien", "plain"]
        np.testing.assert_array_equal(back.features, ds.features)

    def test_write_arff_rejects_unrepresentable_value(self, tmp_path):
        # Adjacent quoted runs concatenate into one token, so "a'b"'c"d'
        # yields a value holding BOTH quote chars — representable on input,
        # not on output (the dialect has no escape syntax).
        ds = parse_py(
            "@relation r\n@attribute who STRING\n@attribute class NUMERIC\n"
            "@data\n\"a'b\"'c\"d',0\n"
        )
        assert ds.attributes[0].string_values == ["a'bc\"d"]
        with pytest.raises(ValueError, match="both quote"):
            write_arff(ds, str(tmp_path / "nope.arff"))

    def test_multiline_row_error_cites_token_line(self):
        # ADVICE r1: a bad value carried from line N must be reported on
        # line N, not on the line that completed the row group.
        text = (
            "@relation r\n@attribute x NUMERIC\n@attribute y NUMERIC\n"
            "@attribute class NUMERIC\n@data\n"
            "1,bogus,\n"   # line 6: the bad token
            "0\n"          # line 7: completes the row
        )
        with pytest.raises(pyarff.ArffError) as ei:
            parse_py(text)
        assert "<test>:6:" in str(ei.value)

    def test_cache_roundtrips_string_tables(self, tmp_path, monkeypatch):
        p = tmp_path / "s.arff"
        p.write_text(STRING_FILE)
        monkeypatch.setenv("KNN_TPU_ARFF_CACHE", str(tmp_path / "cache"))
        first = load_arff(str(p), use_native=False)
        cached = load_arff(str(p), use_native=False)  # hits the npz cache
        np.testing.assert_array_equal(cached.features, first.features)
        assert [a.string_values for a in cached.attributes] == [
            a.string_values for a in first.attributes
        ]
