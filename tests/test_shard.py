"""Mesh-sharded serving (``knn_tpu/shard/``, PR 18).

Pins the tentpole contract and its satellites:

- shard plans are pure, deterministic functions of (size, shards) with
  contiguous boundaries — incl. the degenerates (1 shard, shards >
  cells/rows, empty delta slices);
- ``models/ordering.lexicographic_topk_jax`` — the device twin every
  cross-shard merge selects through — is pinned against the host
  contract on adversarial tie plateaus (the satellite-1 rebase of
  ``train_sharded.merge_candidates_vote``);
- sharded retrieval is BIT-identical to the single-device rungs across
  families × exact/ivf × mutable on/off, on the tie/NaN fixtures;
- a ``url1+url2`` fleet shard group is usable only while EVERY member
  is healthy (the kill-one-member drill's routing contract);
- ``ServeApp(shards=N)`` serves the sharded twin; ``shards=None``
  constructs no shard machinery at all.
"""

from __future__ import annotations

import shutil
import time

import numpy as np
import pytest

from knn_tpu.data.dataset import Dataset
from knn_tpu.index.ivf import IVF_ATTR, IVFIndex, IVFServing
from knn_tpu.models.knn import KNNClassifier, KNNRegressor
from knn_tpu.models.ordering import lexicographic_topk
from knn_tpu.mutable.engine import MutableEngine
from knn_tpu.serve.artifact import save_index
from knn_tpu.serve.batcher import MicroBatcher
from knn_tpu.shard import plan as plan_mod
from knn_tpu.shard.model import make_sharded


@pytest.fixture
def rng():
    return np.random.default_rng(11)


def _tie_problem(rng, n=400, d=6, q=24):
    """Grid-valued features -> plentiful exact distance ties, plus an
    exact-match query and a NaN query (the adversarial corners) — the
    same fixture shape test_device_path.py pins the device scorer on."""
    x = rng.integers(0, 4, (n, d)).astype(np.float32)
    h = min(10, n // 4)
    x[4 * h - h:4 * h] = x[0:h]  # duplicate rows: exact ties across shards
    qx = rng.integers(0, 4, (q, d)).astype(np.float32)
    qx[1] = x[min(17, n - 1)]  # exact match (distance 0 ties)
    qx[3, 2] = np.nan   # NaN query -> all +inf, ties broken by index
    return x, qx


def _qds(qx):
    """Queries as a Dataset (the model-layer API takes datasets)."""
    return Dataset(qx, np.zeros(qx.shape[0], np.int32))


def _assert_bitwise(a, b, what=""):
    d1, i1 = a
    d2, i2 = b
    np.testing.assert_array_equal(i1, i2, err_msg=f"{what}: indices")
    assert (np.asarray(d1, np.float32).view(np.uint32)
            == np.asarray(d2, np.float32).view(np.uint32)).all(), \
        f"{what}: distances not bit-identical"


class TestShardPlan:
    def test_plan_rows_balanced_and_deterministic(self):
        p = plan_mod.plan_rows(10, 3)
        assert p.row_starts == (0, 4, 7, 10)
        assert p == plan_mod.plan_rows(10, 3)  # pure function
        widths = [p.rows(s)[1] - p.rows(s)[0] for s in range(p.num_shards)]
        assert max(widths) - min(widths) <= 1
        assert p.export()["rows_per_shard"] == [4, 3, 3]

    def test_plan_rows_degenerates(self):
        assert plan_mod.plan_rows(5, 1).row_starts == (0, 5)
        # shards > rows collapses to one-row shards, never empty ones
        p = plan_mod.plan_rows(3, 500)
        assert p.num_shards == 3
        assert p.row_starts == (0, 1, 2, 3)
        assert plan_mod.plan_rows(0, 4).row_starts == (0, 0)
        with pytest.raises(ValueError):
            plan_mod.plan_rows(10, 0)

    def test_plan_cells_owns_whole_cells(self, rng):
        sizes = rng.integers(1, 40, 17)
        offsets = np.concatenate([[0], np.cumsum(sizes)])
        p = plan_mod.plan_cells(offsets, 5)
        assert p.cell_starts[0] == 0 and p.cell_starts[-1] == 17
        for s in range(p.num_shards):
            c0, c1 = p.cells(s)
            assert c1 > c0  # every shard keeps >= 1 cell
            # row boundary sits exactly on a cell boundary: a probed
            # cell belongs WHOLLY to one shard.
            assert p.rows(s) == (int(offsets[c0]), int(offsets[c1]))
        assert p == plan_mod.plan_cells(offsets, 5)

    def test_plan_cells_more_shards_than_cells(self):
        offsets = np.array([0, 3, 5, 9])
        p = plan_mod.plan_cells(offsets, 64)
        assert p.num_shards == 3  # clamped: one cell per shard
        assert p.cell_starts == (0, 1, 2, 3)
        assert p.row_starts == (0, 3, 5, 9)

    def test_plan_delta_quota_and_empty_slices(self):
        assert plan_mod.plan_delta(7, 3) == ((0, 3), (3, 5), (5, 7))
        # shards past the live count get EMPTY slices, the plan never
        # shrinks with the delta fill level
        assert plan_mod.plan_delta(2, 4) == ((0, 1), (1, 2), (2, 2), (2, 2))
        assert plan_mod.plan_delta(0, 2) == ((0, 0), (0, 0))

    def test_plan_rows_uniform_matches_device_clip_rule(self):
        for n, stride, shards in [(403, 51, 8), (6, 51, 8), (0, 4, 3),
                                  (4096, 512, 8)]:
            p = plan_mod.plan_rows_uniform(n, shards, stride)
            for s in range(shards):
                r0, r1 = p.rows(s)
                want = int(np.clip(n - s * stride, 0, stride))
                assert r1 - r0 == want, (n, stride, s)
        with pytest.raises(ValueError):
            plan_mod.plan_rows_uniform(10, 0, 4)
        with pytest.raises(ValueError):
            plan_mod.plan_rows_uniform(10, 2, 0)

    def test_effective_shards_clamps(self):
        assert plan_mod.effective_shards(8, 3) == 3
        assert plan_mod.effective_shards(2, 100) == 2
        assert plan_mod.effective_shards(4, 0) == 1
        with pytest.raises(ValueError):
            plan_mod.effective_shards(0, 5)


class TestLexicographicDeviceTwin:
    """The satellite-1 pin: the device realization of the (distance,
    index) contract — which every cross-shard merge selects through —
    equals the host helper on adversarial tie plateaus."""

    def _plateau(self, rng, q=8, m=96):
        # Three-valued distances -> huge plateaus; +inf padding rows;
        # an all-equal row (total plateau); shuffled global indices.
        d = rng.choice(np.array([0.0, 1.0, np.inf], np.float32),
                       (q, m), p=[0.45, 0.45, 0.1])
        d[0] = 1.0
        idx = np.stack([rng.permutation(m) for _ in range(q)]).astype(
            np.int32)
        return d, idx

    def test_device_equals_host_on_plateaus(self, rng):
        import jax

        from knn_tpu.models.ordering import lexicographic_topk_jax

        d, idx = self._plateau(rng)
        for k in (1, 5, 64, 96):
            hd, hi = lexicographic_topk(d, idx, k)
            dd, di = jax.jit(
                lambda a, b, kk=k: lexicographic_topk_jax(a, b, kk)
            )(d, idx)
            _assert_bitwise((hd, hi), (np.asarray(dd), np.asarray(di)),
                            f"k={k}")

    def test_payload_rides_the_same_permutation(self, rng):
        import jax

        from knn_tpu.models.ordering import lexicographic_topk_jax

        d, idx = self._plateau(rng)
        labels = (idx % 7).astype(np.int32)
        dd, di, dl = jax.jit(
            lambda a, b, c: lexicographic_topk_jax(a, b, 10, c)
        )(d, idx, labels)
        np.testing.assert_array_equal(np.asarray(dl),
                                      np.asarray(di) % 7)

    def test_merge_candidates_vote_is_shard_order_invariant(self, rng):
        # The same candidate multiset split at different shard
        # boundaries must vote identically — and identically to the
        # host contract's top-k labels.
        import jax.numpy as jnp

        from knn_tpu.ops.vote import vote
        from knn_tpu.parallel.train_sharded import merge_candidates_vote

        d, idx = self._plateau(rng, q=6, m=60)
        labels = (idx % 4).astype(np.int32)
        k, C = 7, 4
        hd, hi = lexicographic_topk(d, idx, k)
        want = np.asarray(vote(jnp.asarray((hi % 4).astype(np.int32)), C))
        for perm_seed in range(3):
            order = np.random.default_rng(perm_seed).permutation(60)
            got = merge_candidates_vote(
                jnp.asarray(d[:, order]), jnp.asarray(idx[:, order]),
                jnp.asarray(labels[:, order]), k, C)
            np.testing.assert_array_equal(np.asarray(got), want)


class TestShardedExactBitIdentity:
    def test_classifier_matrix_vs_single_device(self, rng):
        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=5, engine="xla").fit(Dataset(x, y))
        qds = _qds(qx)
        want = model.kneighbors(qds)
        want_pred = model.predict(qds)
        for s in (1, 2, 3, 7):
            sm = make_sharded(model, s)
            assert sm.shard_plan_.num_shards == s
            _assert_bitwise(want, sm.kneighbors(qds), f"shards={s}")
            np.testing.assert_array_equal(sm.predict(qds), want_pred)

    def test_regressor_vs_single_device(self, rng):
        x, qx = _tie_problem(rng)
        y = rng.standard_normal(x.shape[0]).astype(np.float32)
        model = KNNRegressor(k=5, engine="xla").fit(Dataset(x, y))
        sm = make_sharded(model, 3)
        qds = _qds(qx)
        _assert_bitwise(model.kneighbors(qds), sm.kneighbors(qds),
                        "regressor")
        np.testing.assert_array_equal(sm.predict(qds), model.predict(qds))

    def test_shards_exceed_rows(self, rng):
        x, qx = _tie_problem(rng, n=10, q=6)
        y = rng.integers(0, 2, 10).astype(np.int32)
        model = KNNClassifier(k=3, engine="xla").fit(Dataset(x, y))
        sm = make_sharded(model, 500)  # clamps to one-row shards
        assert sm.shard_plan_.num_shards == 10
        _assert_bitwise(model.kneighbors(_qds(qx)), sm.kneighbors(_qds(qx)),
                        "shards>rows")

    def test_k_exceeds_per_shard_candidates(self, rng):
        x, qx = _tie_problem(rng, n=30, q=8)
        y = rng.integers(0, 2, 30).astype(np.int32)
        model = KNNClassifier(k=20, engine="xla").fit(Dataset(x, y))
        sm = make_sharded(model, 7)  # ~4 rows/shard << k
        _assert_bitwise(model.kneighbors(_qds(qx)), sm.kneighbors(_qds(qx)),
                        "k>per-shard rows")

    def test_unfitted_model_raises(self):
        with pytest.raises(RuntimeError, match="fit"):
            make_sharded(KNNClassifier(k=3), 2)

    def test_shard_metrics_registered(self, rng):
        from knn_tpu import obs

        x, qx = _tie_problem(rng, n=120, q=4)
        y = rng.integers(0, 2, 120).astype(np.int32)
        model = KNNClassifier(k=3, engine="xla").fit(Dataset(x, y))
        obs.reset()
        obs.enable()
        try:
            make_sharded(model, 3).kneighbors(_qds(qx))
            names = set(obs.registry().to_json())
        finally:
            obs.disable()
            obs.reset()
        for want in ("knn_shard_dispatch_ms", "knn_shard_dispatch_ms_max",
                     "knn_shard_dispatch_ms_min", "knn_shard_dispatch_skew",
                     "knn_shard_candidates_total", "knn_shard_bytes_total"):
            assert want in names, (want, sorted(names))


class TestShardedIVFBitIdentity:
    def test_device_scorer_matrix(self, rng, monkeypatch):
        monkeypatch.setenv("KNN_TPU_IVF_SCORER", "device")
        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=5, engine="xla").fit(Dataset(x, y))
        setattr(model, IVF_ATTR, IVFIndex.build(x, 16, seed=0))
        for s in (1, 3, 50):  # 50 > 16 cells: clamps to one-cell shards
            sm = make_sharded(model, s)
            if s > 16:
                assert sm.ivf_.shard_plan.num_shards == 16
            for k, nprobe in [(1, 1), (5, 4), (10, 16)]:
                want = model.ivf_.search(x, qx, k, nprobe, scorer="host")
                got = sm.ivf_.search(x, qx, k, nprobe, scorer="device")
                _assert_bitwise(want[:2], got[:2],
                                f"shards={s} k={k} nprobe={nprobe}")

    def test_serving_rung_through_sharded_model(self, rng, monkeypatch):
        monkeypatch.setenv("KNN_TPU_IVF_SCORER", "device")
        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=5, engine="xla").fit(Dataset(x, y))
        setattr(model, IVF_ATTR, IVFIndex.build(x, 16, seed=0))
        serving = IVFServing(4, 16)
        want = serving.kneighbors(model, qx)
        got = serving.kneighbors(make_sharded(model, 3), qx)
        _assert_bitwise(want, got, "ivf serving rung")


def _sharded_vs_plain_batchers(model, num_shards, tmp_path, **kw):
    """Two MicroBatchers over byte-identical artifacts: the sharded twin
    vs the plain model, each with its own device-tail mutable engine —
    the live end-to-end bit-identity harness."""
    root_a = tmp_path / "idx-sharded"
    save_index(model, root_a, ivf=getattr(model, IVF_ATTR, None))
    root_b = tmp_path / "idx-plain"
    shutil.copytree(root_a, root_b)
    eng_a = MutableEngine(model, root_a, delta_cap=256,
                          device_tail="on", **kw)
    eng_b = MutableEngine(model, root_b, delta_cap=256,
                          device_tail="on", **kw)
    b_sh = MicroBatcher(make_sharded(model, num_shards), max_batch=64,
                        max_wait_ms=0.0, mutable=eng_a)
    b_pl = MicroBatcher(model, max_batch=64, max_wait_ms=0.0,
                        mutable=eng_b)
    return b_sh, b_pl


class TestShardedMutableBitIdentity:
    def test_merged_serving_matches_single_device(self, rng, tmp_path):
        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=5, engine="xla").fit(Dataset(x, y))
        b_sh, b_pl = _sharded_vs_plain_batchers(model, 3, tmp_path)
        try:
            # immutable baseline first
            _assert_bitwise(b_pl.kneighbors(qx, timeout=60),
                            b_sh.kneighbors(qx, timeout=60), "no delta")
            # insert -> fused delta-tail shards ride the dispatch
            rows = rng.standard_normal((30, x.shape[1])).astype(np.float32)
            vals = rng.integers(0, 3, 30).astype(np.float32)
            for b in (b_sh, b_pl):
                b.submit_mutation("insert",
                                  {"rows": rows, "values": vals}).result(
                    timeout=60)
            _assert_bitwise(b_pl.kneighbors(qx, timeout=60),
                            b_sh.kneighbors(qx, timeout=60), "insert")
            np.testing.assert_array_equal(b_sh.predict(qx, timeout=60),
                                          b_pl.predict(qx, timeout=60))
            # delta delete: the dead slot is masked on whichever shard
            # owns its slice
            for b in (b_sh, b_pl):
                b.submit_mutation("delete",
                                  {"ids": [x.shape[0] + 1]}).result(
                    timeout=60)
            d1, i1 = b_sh.kneighbors(qx, timeout=60)
            _assert_bitwise(b_pl.kneighbors(qx, timeout=60), (d1, i1),
                            "delta delete")
            assert not (np.asarray(i1) == x.shape[0] + 1).any()
            # base tombstone: documented host-merge fallback, still
            # bit-identical end to end
            for b in (b_sh, b_pl):
                b.submit_mutation("delete", {"ids": [17]}).result(
                    timeout=60)
            d2, i2 = b_sh.kneighbors(qx, timeout=60)
            _assert_bitwise(b_pl.kneighbors(qx, timeout=60), (d2, i2),
                            "base tombstone")
            assert not (np.asarray(i2) == 17).any()
        finally:
            b_sh.close()
            b_pl.close()

    def test_ivf_fused_delta_matches_single_device(self, rng, tmp_path,
                                                   monkeypatch):
        monkeypatch.setenv("KNN_TPU_IVF_SCORER", "device")
        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=4, engine="xla").fit(Dataset(x, y))
        setattr(model, IVF_ATTR, IVFIndex.build(x, 12, seed=0))
        sm = make_sharded(model, 4)
        root = tmp_path / "idx"
        save_index(model, root, ivf=model.ivf_)
        eng = MutableEngine(model, root, delta_cap=256, device_tail="on")
        rows = rng.standard_normal((30, x.shape[1])).astype(np.float32)
        eng.apply_insert(rows, rng.integers(0, 3, 30).astype(np.float32),
                         time.monotonic_ns())
        view = eng.snapshot()
        serving = IVFServing(4, 12)
        want = serving.kneighbors(model, qx, view=view)
        got = serving.kneighbors(sm, qx, view=view)
        _assert_bitwise(want, got, "sharded ivf fused delta")


class TestFleetShardGroups:
    def _set(self, specs):
        from knn_tpu.fleet.health import ReplicaSet

        return ReplicaSet(specs, interval_s=999, poll_timeout_s=1)

    def test_spec_parsing_heads_and_members(self):
        rs = self._set(["http://a:1+http://a:2", "http://b:1"])
        assert rs.urls == ["http://a:1", "http://b:1"]
        assert rs.groups == {"http://a:1": ("http://a:1", "http://a:2")}
        assert set(rs._states) == {"http://a:1", "http://a:2",
                                   "http://b:1"}

    def test_duplicate_member_across_specs_rejected(self):
        with pytest.raises(ValueError, match="duplicate"):
            self._set(["http://x:1+http://x:2", "http://x:2"])

    def test_group_usable_only_when_every_member_healthy(self):
        rs = self._set(["http://a:1+http://a:2", "http://b:1"])
        for s in rs._states.values():
            s.healthy = True
        assert rs.usable_urls() == ["http://a:1", "http://b:1"]
        # kill the NON-head member: the head's own poll stays 200, but
        # the group must look down to routing (a partial shard group
        # cannot answer from its whole index)
        rs._states["http://a:2"].healthy = False
        assert not rs.is_healthy("http://a:1")
        assert rs.usable_urls() == ["http://b:1"]
        rs._states["http://a:2"].healthy = True
        assert rs.is_healthy("http://a:1")

    def test_group_gates_failover_queries_and_export(self):
        rs = self._set(["http://a:1+http://a:2", "http://b:1"])
        for s in rs._states.values():
            s.healthy = True
        rs._states["http://a:1"].role = "primary"
        rs._states["http://b:1"].role = "follower"
        assert rs.primary_url() == "http://a:1"
        rs._states["http://a:2"].healthy = False
        assert rs.primaries() == []
        assert rs.down_primary() == "http://a:1"  # failover trigger
        assert rs.most_caught_up() == "http://b:1"
        doc = rs.export()
        head = doc["replicas"]["http://a:1"]
        assert head["shard_group"]["members"] == ["http://a:1",
                                                  "http://a:2"]
        assert head["shard_group"]["unhealthy"] == ["http://a:2"]
        assert head["healthy"] is False  # the GROUP's usability
        assert doc["usable"] == 1
        assert "shard_group" not in doc["replicas"]["http://b:1"]


class TestServeAppSharding:
    def test_sharded_app_serves_bit_identical(self, rng):
        from knn_tpu.serve.server import ServeApp

        x, qx = _tie_problem(rng, n=200, q=8)
        y = rng.integers(0, 3, 200).astype(np.int32)
        model = KNNClassifier(k=4, engine="xla").fit(Dataset(x, y))
        plain = KNNClassifier(k=4, engine="xla").fit(Dataset(x, y))
        app = ServeApp(model, max_batch=16, max_wait_ms=0.0, shards=2)
        ref = ServeApp(plain, max_batch=16, max_wait_ms=0.0)
        try:
            assert app.shards == 2
            np.testing.assert_array_equal(
                app.batcher.predict(qx, timeout=60),
                ref.batcher.predict(qx, timeout=60))
            block = app.health()["shard"]
            assert block["num_shards"] == 2
            assert sum(block["rows_per_shard"]) == 200
        finally:
            app.close()
            ref.close()

    def test_unsharded_app_constructs_nothing(self, rng):
        from knn_tpu.serve.server import ServeApp

        x, _ = _tie_problem(rng, n=60, q=4)
        y = rng.integers(0, 2, 60).astype(np.int32)
        model = KNNClassifier(k=3, engine="xla").fit(Dataset(x, y))
        app = ServeApp(model, max_batch=8, max_wait_ms=0.0)
        try:
            assert app.shards is None
            assert app.health()["shard"] is None
            assert not hasattr(app.model, "shard_plan_")
        finally:
            app.close()
