"""Answer-quality observability contracts (docs/OBSERVABILITY.md §Quality
& drift).

The load-bearing claims:

- the streaming estimators are HONEST: P² quantiles and Welford moments
  track numpy on fixed seeds, recall@k scores hand-built neighbor lists
  correctly under the shared (distance, index) contract (ties included);
- the shadow path NEVER blocks serving: a full sample queue sheds
  (counted) with the producer returning immediately, pinned with the
  scoring worker held off;
- detection works end to end: exact rungs score recall 1.0 / zero
  divergence / zero quality burn, while a corrupted index is caught and
  attributed to the answering rung — the proof the scorer would catch a
  bad approximate rung before ROADMAP item 4 ships one;
- the no-baseline drift state is DISTINCT from zero drift (the artifact
  back-compat guard).
"""

import time

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.obs.drift import (
    DriftMonitor,
    P2Quantile,
    StreamSketch,
    drift_scores,
    sketch_summary,
)
from knn_tpu.obs.quality import (
    ShadowScorer,
    recall_at_k,
    true_distances,
)
from knn_tpu.obs.slo import SLOTracker
from knn_tpu.serve.batcher import MicroBatcher


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


def _problem(rng, n=200, d=5, c=3):
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)  # grid -> ties
    train_y = rng.integers(0, c, n).astype(np.int32)
    return Dataset(train_x, train_y)


# ---------------------------------------------------------------------------
# P² quantile estimator vs numpy


class TestP2Quantile:
    @pytest.mark.parametrize("p", [0.25, 0.5, 0.75, 0.9])
    @pytest.mark.parametrize("dist", ["normal", "uniform", "exponential"])
    def test_tracks_numpy_on_fixed_seeds(self, p, dist):
        rng = np.random.default_rng(42)
        xs = getattr(rng, dist)(size=5000)
        est = P2Quantile(p)
        for x in xs:
            est.update(x)
        want = float(np.quantile(xs, p))
        spread = float(np.quantile(xs, 0.9) - np.quantile(xs, 0.1))
        # P² is an approximation: within a few percent of the 10-90 spread
        # at n=5000 (the classical accuracy claim, loose enough for CI).
        assert est.value == pytest.approx(want, abs=0.05 * spread)

    def test_small_n_is_exact(self):
        est = P2Quantile(0.5)
        for x in (5.0, 1.0, 3.0):
            est.update(x)
        assert est.value == pytest.approx(3.0)
        assert P2Quantile(0.5).value is None

    def test_five_values_exact_median(self):
        est = P2Quantile(0.5)
        for x in (9.0, 1.0, 7.0, 3.0, 5.0):
            est.update(x)
        assert est.value == pytest.approx(5.0)

    def test_rejects_bad_p(self):
        with pytest.raises(ValueError, match="quantile"):
            P2Quantile(0.0)
        with pytest.raises(ValueError, match="quantile"):
            P2Quantile(1.0)


# ---------------------------------------------------------------------------
# StreamSketch: Welford moments + serialization


class TestStreamSketch:
    def test_welford_matches_numpy_in_chunks(self):
        rng = np.random.default_rng(7)
        data = rng.normal(3.0, 2.0, (1000, 4)) * np.array([1.0, 10.0, 0.1, 5])
        s = StreamSketch(4)
        for lo in range(0, 1000, 37):  # ragged chunk sizes
            s.update(data[lo:lo + 37])
        assert s.count == 1000
        np.testing.assert_allclose(s.mean(), data.mean(axis=0), rtol=1e-10)
        np.testing.assert_allclose(
            s.variance(), data.var(axis=0, ddof=1), rtol=1e-9)

    def test_p2_quartiles_track_numpy(self):
        rng = np.random.default_rng(3)
        data = rng.normal(0.0, 1.0, (4000, 2))
        s = StreamSketch(2)
        for lo in range(0, 4000, 256):
            s.update(data[lo:lo + 256])
        for p in (0.25, 0.5, 0.75):
            got = np.asarray(s.quantile(p), np.float64)
            want = np.quantile(data, p, axis=0)
            np.testing.assert_allclose(got, want, atol=0.1)

    def test_from_data_is_exact(self):
        rng = np.random.default_rng(1)
        data = rng.normal(size=(321, 3))
        s = StreamSketch.from_data(data)
        assert s.count == 321
        np.testing.assert_allclose(s.mean(), data.mean(axis=0), rtol=1e-12)
        np.testing.assert_allclose(
            s.variance(), data.var(axis=0, ddof=1), rtol=1e-12)
        for p in (0.25, 0.5, 0.75):
            np.testing.assert_allclose(
                np.asarray(s.quantile(p)), np.quantile(data, p, axis=0),
                rtol=1e-12)

    def test_serialization_round_trip(self):
        rng = np.random.default_rng(2)
        data = rng.normal(size=(100, 3))
        doc = StreamSketch.from_data(data).to_dict()
        norm = sketch_summary(doc)
        assert norm["count"] == 100 and norm["num_features"] == 3
        np.testing.assert_allclose(norm["mean"], data.mean(axis=0),
                                   atol=1e-7)
        assert set(norm["quantiles"]) == {0.25, 0.5, 0.75}

    def test_malformed_sketch_rejected(self):
        with pytest.raises((ValueError, KeyError, TypeError)):
            sketch_summary({"num_features": 3, "count": 1,
                            "mean": [1.0], "var": [1.0, 1.0, 1.0]})
        with pytest.raises(ValueError):
            sketch_summary("not a sketch")

    def test_feature_width_enforced(self):
        s = StreamSketch(3)
        with pytest.raises(ValueError, match="features"):
            s.update(np.zeros((2, 4)))


# ---------------------------------------------------------------------------
# recall@k on hand-built neighbor lists (the shared (distance, index)
# contract, ties included)


class TestRecallAtK:
    def test_exact_match_is_one(self):
        oracle_i = np.array([[0, 1], [2, 3]])
        oracle_d = np.array([[0.0, 1.0], [2.0, 3.0]])
        r = recall_at_k(oracle_i, oracle_i, oracle_d, oracle_d)
        np.testing.assert_allclose(r, [1.0, 1.0])

    def test_tie_broken_the_other_way_is_not_a_loss(self):
        # Train rows 1 and 2 are equidistant (d=1). The oracle's
        # (distance, index) order picks index 1; a served list that chose
        # index 2 — true distance 1, tying the oracle's k-th — is still
        # recall 1.0: an equally-near neighbor is not a miss.
        oracle_i = np.array([[0, 1]])
        oracle_d = np.array([[0.0, 1.0]])
        served_i = np.array([[0, 2]])
        true_d = np.array([[0.0, 1.0]])  # recomputed: index 2 IS at d=1
        np.testing.assert_allclose(
            recall_at_k(served_i, oracle_i, oracle_d, true_d), [1.0])

    def test_wrong_neighbor_counts_against(self):
        oracle_i = np.array([[0, 1]])
        oracle_d = np.array([[0.0, 1.0]])
        served_i = np.array([[0, 7]])
        true_d = np.array([[0.0, 9.0]])  # index 7 is genuinely far
        np.testing.assert_allclose(
            recall_at_k(served_i, oracle_i, oracle_d, true_d), [0.5])

    def test_claimed_distance_cannot_fake_a_tie(self):
        # The tie clause uses the RECOMPUTED distance: a served index that
        # claims d=1.0 but actually sits at d=9.0 is a miss.
        oracle_i = np.array([[0, 1]])
        oracle_d = np.array([[0.0, 1.0]])
        served_i = np.array([[0, 7]])
        true_d = np.array([[0.0, 9.0]])
        r = recall_at_k(served_i, oracle_i, oracle_d, true_d)
        np.testing.assert_allclose(r, [0.5])

    def test_duplicate_served_indices_count_once(self):
        # A degenerate list repeating the true nearest neighbor k times
        # recalled ONE neighbor, not k — each distinct train index counts
        # at most once (the failure mode a buggy approximate rung would
        # otherwise hide behind).
        oracle_i = np.array([[0, 1, 2]])
        oracle_d = np.array([[0.0, 1.0, 2.0]])
        served_i = np.array([[0, 0, 0]])
        true_d = np.array([[0.0, 0.0, 0.0]])
        np.testing.assert_allclose(
            recall_at_k(served_i, oracle_i, oracle_d, true_d), [1 / 3])

    def test_shape_mismatch_raises(self):
        with pytest.raises(ValueError, match="shapes differ"):
            recall_at_k(np.zeros((1, 2)), np.zeros((1, 3)),
                        np.zeros((1, 3)), np.zeros((1, 2)))

    def test_true_distances_match_oracle_on_own_candidates(self, rng):
        from knn_tpu.backends.oracle import oracle_kneighbors

        train = rng.normal(size=(50, 4)).astype(np.float32)
        queries = rng.normal(size=(6, 4)).astype(np.float32)
        d, i = oracle_kneighbors(train, queries, 3)
        td = true_distances(train, queries, i, "euclidean")
        np.testing.assert_allclose(td, d, rtol=1e-6)


# ---------------------------------------------------------------------------
# Drift scoring


class TestDriftScores:
    def test_identical_distributions_score_zero(self):
        rng = np.random.default_rng(5)
        data = rng.normal(size=(500, 3))
        ref = sketch_summary(StreamSketch.from_data(data).to_dict())
        live = sketch_summary(StreamSketch.from_data(data).to_dict())
        s = drift_scores(ref, live)
        np.testing.assert_allclose(s, 0.0, atol=1e-6)

    def test_mean_shift_scores_in_sigma_units(self):
        rng = np.random.default_rng(6)
        data = rng.normal(0.0, 1.0, (2000, 2))
        shifted = data + np.array([3.0, 0.0])
        ref = sketch_summary(StreamSketch.from_data(data).to_dict())
        live = sketch_summary(StreamSketch.from_data(shifted).to_dict())
        s = drift_scores(ref, live)
        assert s[0] == pytest.approx(3.0, rel=0.3)
        assert s[1] < 0.3

    def test_constant_reference_feature_does_not_blow_up(self):
        data = np.ones((100, 1))
        live_data = np.full((100, 1), 2.0)
        ref = sketch_summary(StreamSketch.from_data(data).to_dict())
        live = sketch_summary(StreamSketch.from_data(live_data).to_dict())
        s = drift_scores(ref, live)
        assert np.all(np.isfinite(s)) and s[0] > 0


class TestDriftMonitor:
    def test_no_baseline_state_is_distinct(self, obs_on):
        m = DriftMonitor(None, rate=1.0, num_features=3, autostart=False)
        m.offer(np.zeros((2, 3), np.float32))
        summary = m.export()
        assert summary["baseline"] == "absent"
        assert summary["scores"] is None  # never fabricated
        present = [i for i in obs_on.instruments()
                   if i.name == "knn_drift_baseline_present"]
        assert len(present) == 1 and present[0].value == 0
        assert not any(i.name == "knn_drift_score"
                       for i in obs_on.instruments())
        m.close()

    def test_live_vs_training_distribution(self, obs_on, rng):
        train = rng.normal(0.0, 1.0, (2000, 3)).astype(np.float32)
        ref = StreamSketch.from_data(train).to_dict()
        m = DriftMonitor(ref, rate=1.0, num_features=3)
        try:
            # Same distribution: low score.
            for lo in range(0, 1000, 50):
                m.offer(train[lo:lo + 50])
            assert m.drain(20)
            same = m.export()["scores"]["max"]
            assert same < 0.5
            # Shifted queries: the score must rise well above.
            m2 = DriftMonitor(ref, rate=1.0, num_features=3)
            try:
                shifted = train[:1000] + 5.0
                for lo in range(0, 1000, 50):
                    m2.offer(shifted[lo:lo + 50])
                assert m2.drain(20)
                far = m2.export()["scores"]["max"]
                assert far > 2.0 > same
            finally:
                m2.close()
        finally:
            m.close()

    def test_shed_on_overload_never_blocks(self, obs_on):
        m = DriftMonitor(None, rate=1.0, num_features=2, queue_cap=2,
                         autostart=False)  # no worker: queue can only fill
        rows = np.zeros((1, 2), np.float32)
        assert m.offer(rows) and m.offer(rows)
        t0 = time.monotonic()
        assert not m.offer(rows)  # full -> shed, immediately
        assert time.monotonic() - t0 < 0.1
        assert m.shed == 1
        shed = [i for i in obs_on.instruments()
                if i.name == "knn_drift_shed_total"]
        assert len(shed) == 1 and shed[0].value == 1
        m.close()

    def test_set_reference_swaps_baseline(self, rng):
        train = rng.normal(size=(100, 2)).astype(np.float32)
        m = DriftMonitor(None, rate=1.0, num_features=2, autostart=False)
        assert not m.baseline_present
        m.set_reference(StreamSketch.from_data(train).to_dict())
        assert m.baseline_present
        m.set_reference(None)  # a pre-sketch rollback
        assert not m.baseline_present
        m.close()

    def test_rate_validation(self):
        with pytest.raises(ValueError, match="rate"):
            DriftMonitor(None, rate=1.5, num_features=1, autostart=False)
        with pytest.raises(ValueError, match="queue_cap"):
            DriftMonitor(None, rate=0.5, num_features=1, queue_cap=0,
                         autostart=False)

    def test_wrong_width_reference_fails_at_boot_not_scrape(self, rng):
        """A manifest sketch whose width disagrees with the index must
        raise at construction/reload time (ValueError -> CLI exit 2 /
        reload rolled back), never as a numpy broadcast error inside the
        first /metrics scrape."""
        ref = StreamSketch.from_data(
            rng.normal(size=(50, 4)).astype(np.float32)).to_dict()
        with pytest.raises(ValueError, match="4 features"):
            DriftMonitor(ref, rate=1.0, num_features=3, autostart=False)
        m = DriftMonitor(None, rate=1.0, num_features=3, autostart=False)
        with pytest.raises(ValueError, match="4 features"):
            m.set_reference(ref)
        assert not m.baseline_present  # the failed swap changed nothing
        m.close()

    def test_malformed_sketch_is_a_value_error(self):
        with pytest.raises(ValueError, match="malformed drift sketch"):
            DriftMonitor({"count": 3}, rate=1.0, num_features=2,
                         autostart=False)

    def test_baseline_removal_zeroes_exported_scores(self, obs_on, rng):
        """A hot reload to a pre-sketch artifact must not leave the
        previous index's drift scores frozen in the registry."""
        train = rng.normal(size=(200, 2)).astype(np.float32)
        ref = StreamSketch.from_data(train).to_dict()
        m = DriftMonitor(ref, rate=1.0, num_features=2, autostart=False)
        with m._sketch_lock:
            m.live.update(train[:50] + 10.0)  # worker held off: fold direct
        assert m.export()["scores"]["max"] > 0
        gauges = {dict(i.labels)["stat"]: i for i in obs_on.instruments()
                  if i.name == "knn_drift_score"}
        assert gauges["max"].value > 0
        m.set_reference(None)  # the pre-sketch rollback
        summary = m.export()
        assert summary["baseline"] == "absent" and summary["scores"] is None
        assert gauges["max"].value == 0.0 and gauges["mean"].value == 0.0
        m.close()


# ---------------------------------------------------------------------------
# ShadowScorer


class TestShadowScorer:
    def test_shed_on_overload_never_blocks_the_producer(self, obs_on, rng):
        train = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        s = ShadowScorer(1.0, queue_cap=2, autostart=False)  # worker off
        feats = train.features[:1]
        kw = dict(features=feats, kind="kneighbors",
                  dists=np.zeros((1, 3)), idx=np.zeros((1, 3), np.int64),
                  preds=None, rung="fast", model=model, version="v1")
        assert s.offer(**kw) and s.offer(**kw)
        t0 = time.monotonic()
        assert not s.offer(**kw)  # full -> shed, immediately, never blocks
        assert time.monotonic() - t0 < 0.1
        assert s.shed == 1
        shed = [i for i in obs_on.instruments()
                if i.name == "knn_quality_shed_total"]
        assert len(shed) == 1 and shed[0].value == 1
        s.close()

    def test_producer_not_blocked_while_worker_scores(self, obs_on, rng):
        """The two-lock contract: offers complete fast even while the
        background worker is mid-score on a queue of samples."""
        train = _problem(rng, n=400)
        model = KNNClassifier(k=5, engine="xla").fit(train)
        s = ShadowScorer(1.0, queue_cap=512)
        d, i = model.kneighbors(Dataset(
            train.features[:50], np.zeros(50, np.int32)))
        kw = dict(features=train.features[:50], kind="kneighbors",
                  dists=d, idx=i, preds=None, rung="fast", model=model,
                  version=None)
        walls = []
        for _ in range(40):
            t0 = time.monotonic()
            s.offer(**kw)
            walls.append(time.monotonic() - t0)
        assert max(walls) < 0.1  # every offer O(1), scoring notwithstanding
        assert s.drain(30)
        s.close()
        assert s.export()["rungs"]["fast"]["recall"] == 1.0

    def test_exact_serving_scores_recall_one(self, obs_on, rng):
        train = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        slo = SLOTracker(windows_s=(60,))
        scorer = ShadowScorer(1.0, seed=0, slo=slo)
        with MicroBatcher(model, max_batch=8, max_wait_ms=0.5,
                          quality=scorer) as b:
            rows = rng.integers(0, 4, (12, 5)).astype(np.float32)
            for r in rows:
                b.predict(r, timeout=30)
            assert scorer.drain(30)
        summary = scorer.export()
        scorer.close()
        fast = summary["rungs"]["fast"]
        assert fast["recall"] == 1.0
        assert fast["vote_accuracy"] == 1.0
        assert fast["divergence"] == {}
        assert slo.burn_rates()["quality"]["1m"] == 0.0
        recall_g = [i for i in obs_on.instruments()
                    if i.name == "knn_quality_recall"]
        assert recall_g and all(g.value == 1.0 for g in recall_g)

    def test_corrupted_index_detected_and_attributed(self, obs_on, rng):
        """THE detection contract: a silently-wrong index (every response
        still 200, availability green) must burn the quality SLI and
        localize to the answering rung."""
        train = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        slo = SLOTracker(windows_s=(60,))
        scorer = ShadowScorer(1.0, seed=0, slo=slo)
        with MicroBatcher(model, max_batch=8, max_wait_ms=0.5,
                          quality=scorer) as b:
            b.corrupt_serving = True  # the quality-soak test hook
            rows = rng.integers(0, 4, (12, 5)).astype(np.float32)
            for r in rows:
                b.predict(r, timeout=30)  # still answers "successfully"
            assert scorer.drain(30)
        summary = scorer.export()
        scorer.close()
        fast = summary["rungs"]["fast"]
        assert fast["recall"] < 1.0
        assert fast["divergence"].get("neighbors", 0) > 0
        assert slo.burn_rates()["quality"]["1m"] > 1.0
        div = {tuple(sorted(dict(i.labels).items())): i.value
               for i in obs_on.instruments()
               if i.name == "knn_quality_divergence_total"}
        assert any(dict(k)["rung"] == "fast" for k in div)

    def test_kneighbors_requests_scored_without_vote(self, obs_on, rng):
        train = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        scorer = ShadowScorer(1.0, seed=0)
        with MicroBatcher(model, max_batch=8, max_wait_ms=0.5,
                          quality=scorer) as b:
            b.kneighbors(train.features[0], timeout=30)
            assert scorer.drain(30)
        summary = scorer.export()
        scorer.close()
        fast = summary["rungs"]["fast"]
        assert fast["recall"] == 1.0 and fast["vote_accuracy"] is None

    def test_sampling_is_seeded_and_deterministic(self, rng):
        draws = []
        for _ in range(2):
            s = ShadowScorer(0.5, seed=123, autostart=False)
            picked = []
            for j in range(50):
                picked.append(s.offer(
                    features=np.zeros((1, 2), np.float32),
                    kind="kneighbors", dists=np.zeros((1, 1)),
                    idx=np.zeros((1, 1), np.int64), preds=None,
                    rung="fast", model=None, version=None))
            s.close()
            draws.append(picked)
        assert draws[0] == draws[1]
        assert 5 < sum(draws[0]) < 45  # actually sampling, not all/none

    def test_score_across_model_snapshot(self, obs_on, rng):
        """A sample carries ITS batch's model: answers served by the old
        index are scored against the old index even after a swap (the
        hot-reload correctness rule)."""
        train_a = _problem(rng)
        train_b = Dataset(train_a.features + 100.0, train_a.labels)
        model_a = KNNClassifier(k=3, engine="xla").fit(train_a)
        model_b = KNNClassifier(k=3, engine="xla").fit(train_b)
        scorer = ShadowScorer(1.0, seed=0, autostart=False)
        d, i = model_a.kneighbors(Dataset(
            train_a.features[:2], np.zeros(2, np.int32)))
        assert scorer.offer(features=train_a.features[:2],
                            kind="kneighbors", dists=d, idx=i, preds=None,
                            rung="fast", model=model_a, version="a")
        # Swap happens before scoring: worker starts late, sample must
        # still score 1.0 because it references model_a, not "the current
        # model".
        scorer._sq.start()
        assert scorer.drain(30)
        scorer.close()
        assert scorer.export()["rungs"]["fast"]["recall"] == 1.0
        del model_b

    def test_rate_validation(self):
        with pytest.raises(ValueError, match="shadow rate"):
            ShadowScorer(0.0, autostart=False)
        with pytest.raises(ValueError, match="shadow rate"):
            ShadowScorer(1.5, autostart=False)
        with pytest.raises(ValueError, match="queue_cap"):
            ShadowScorer(0.5, queue_cap=0, autostart=False)

    def test_scoring_errors_counted_not_raised(self, obs_on):
        scorer = ShadowScorer(1.0, seed=0)
        # model=None makes _score raise; the worker must absorb it.
        assert scorer.offer(features=np.zeros((1, 2), np.float32),
                            kind="kneighbors", dists=np.zeros((1, 1)),
                            idx=np.zeros((1, 1), np.int64), preds=None,
                            rung="fast", model=None, version=None)
        deadline = time.monotonic() + 10
        while scorer.score_errors == 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        scorer.close()
        assert scorer.score_errors == 1
        errs = [i for i in obs_on.instruments()
                if i.name == "knn_quality_errors_total"]
        assert len(errs) == 1 and errs[0].value == 1


# ---------------------------------------------------------------------------
# The quality SLI in the SLO tracker


class TestQualitySLO:
    def test_quality_burn_from_shadow_events(self):
        s = SLOTracker(quality_target=0.9, windows_s=(60,))
        for good in (True, True, False, False):
            s.record_quality(good)
        burns = s.burn_rates()
        # 50% bad / 10% budget = burn 5.
        assert burns["quality"]["1m"] == pytest.approx(5.0)
        # HTTP-outcome SLIs are untouched by quality events.
        assert burns["availability"]["1m"] == 0.0

    def test_http_outcomes_do_not_move_quality(self):
        s = SLOTracker(windows_s=(60,))
        for _ in range(10):
            s.record(ok=True, latency_ms=1.0)
        assert s.burn_rates()["quality"]["1m"] == 0.0  # no scored events

    def test_quality_target_validated_and_exported(self):
        with pytest.raises(ValueError, match="quality_target"):
            SLOTracker(quality_target=1.0)
        doc = SLOTracker(windows_s=(60,)).export()
        assert "quality" in doc["burn_rates"]
        assert doc["targets"]["quality"] == 0.999
