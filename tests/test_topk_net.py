"""Correctness of the compile-time top-k merge networks (ops/topk_net.py).

The programs are pure data; these tests validate them on host scalars. The
0-1 principle (Knuth 5.3.4) makes the small exhaustive sweeps PROOFS for
every (g, k) they cover: a comparator network computes the sorted top-k of
every input iff it does so for every 0-1 input, and truncation is covered
because the tested property is end-to-end (output == sorted(all inputs)[:k]).
"""

import itertools
import random

from knn_tpu.ops.topk_net import program_cost, simulate, tile_topk_program


def run_program(g, k, fresh_vals, running_vals, finite=False):
    ops, out = tile_topk_program(g, k, finite)
    vals = list(fresh_vals) + sorted(running_vals)
    result = simulate(ops, vals)
    return [result[w] for w in out]


def check_case(g, k, fresh_vals, running_vals, finite=False):
    got = run_program(g, k, fresh_vals, running_vals, finite)
    want = sorted(list(fresh_vals) + list(running_vals))[:k]
    assert got == want, (g, k, fresh_vals, running_vals, finite, got, want)


def check_both(g, k, fresh_d, running_d):
    """Validate BOTH program variants from distance patterns, each under
    its own contract. finite=False takes arbitrary index encodings (here:
    running indices ABOVE fresh — the adversarial direction for any bogus
    dominance assumption). finite=True additionally requires its gate:
    running candidate indices sit BELOW every fresh index (candidates come
    from earlier tiles) and +inf appears only with the INT_MAX sentinel —
    encoded accordingly."""
    check_case(
        g, k,
        [(d, i) for i, d in enumerate(fresh_d)],
        [(d, 100 + i) for i, d in enumerate(running_d)],
        finite=False,
    )
    inf = float("inf")
    imax = 2**31 - 1
    check_case(
        g, k,
        [(d, imax if d == inf else 1000 + i) for i, d in enumerate(fresh_d)],
        [(d, imax if d == inf else i) for i, d in enumerate(running_d)],
        finite=True,
    )


class TestTileTopkProgram:
    def test_zero_one_exhaustive_small(self):
        # Every 0-1 assignment of the g fresh + k running wires (running
        # sorted, as the kernel invariant guarantees) for every small shape,
        # for BOTH program variants: by the 0-1 principle this proves the
        # comparator structure for these (g, k), and the dense value ties
        # (only 0 and 1!) hammer every resolved tie mode.
        for g in range(1, 9):
            for k in range(1, 6):
                for bits in itertools.product((0, 1), repeat=g):
                    for ones in range(k + 1):
                        running_d = [0 if i < k - ones else 1 for i in range(k)]
                        check_both(g, k, list(bits), running_d)

    def test_zero_one_exhaustive_bench_shapes(self):
        # The bench shapes are too wide for full exhaustion; exhaust the 0-1
        # patterns of a sliding window of fresh wires (others pinned) plus
        # every running fill level — covers every comparator the window
        # touches. g=16 (headline block_n=2048), g=8 (mnist block_n=1024),
        # g=96 (xl block_n=12288, k=10).
        for g, k in ((16, 5), (8, 5), (96, 10)):
            for lo in range(0, g - 3, 3):
                for bits in itertools.product((0, 1), repeat=4):
                    fresh_d = [1] * g
                    for off, b in enumerate(bits):
                        fresh_d[lo + off] = b
                    for ones in (0, k // 2, k):
                        running_d = [
                            0 if i < k - ones else 1 for i in range(k)
                        ]
                        check_both(g, k, fresh_d, running_d)

    def test_random_with_heavy_ties(self):
        # Lexicographic (d, i) semantics under dense ties: the kept set and
        # its order must match a stable host sort — first-seen-wins on equal
        # distances (main.cpp:47).
        rng = random.Random(0)
        for _ in range(400):
            g = rng.randint(1, 24)
            k = rng.randint(1, 10)
            check_both(
                g, k,
                [rng.randint(0, 3) for _ in range(g)],
                [rng.randint(0, 3) for _ in range(k)],
            )

    def test_multi_tile_stream_matches_exact(self):
        # Chain the per-tile program the way the kernel streams tiles: the
        # output levels become the next tile's running wires. Validates the
        # finite=True dominance facts end-to-end — candidate indices really
        # do come from earlier tiles, exactly the gate's premise — against
        # exact sorted selection over the whole stream. Dense ties.
        rng = random.Random(7)
        inf = float("inf")
        imax = 2**31 - 1
        for trial in range(60):
            g = rng.choice([4, 8, 16])
            k = rng.choice([3, 5, 10])
            tiles = rng.randint(2, 5)
            for finite in (False, True):
                ops, out = tile_topk_program(g, k, finite)
                running = [(inf, imax)] * k
                seen = []
                for t in range(tiles):
                    base = t * g
                    # Masked (sentinel) wires are a SUFFIX of the tile —
                    # the kernel invariant both program variants' fresh-wire
                    # dominance facts rely on (a later wire's global column
                    # is larger, so it cannot be valid where an earlier one
                    # is not). NaN-policy +inf with a REAL index may appear
                    # anywhere BEFORE the cut (finite=False only).
                    cut = rng.randint(0, g)
                    fresh = []
                    for c in range(g):
                        if c >= cut:
                            fresh.append((inf, imax))
                        elif finite:
                            fresh.append((rng.randint(0, 3), base + c))
                        else:
                            d = rng.choice([0, 1, 2, inf])
                            fresh.append((d, base + c))
                    seen += [v for v in fresh if v[1] != imax]
                    vals = fresh + list(running)
                    res = simulate(ops, vals)
                    running = [res[w] for w in out]
                want = sorted(seen)[:k]
                got = [v for v in running if v != (inf, imax)][: len(want)]
                assert got == want, (g, k, finite, trial, got, want)

    def test_inf_padding_flows(self):
        # +inf/INT_MAX padding (masked lanes, init levels) must lose to any
        # finite candidate and tie harmlessly among themselves.
        inf = float("inf")
        imax = 2**31 - 1
        fresh = [(inf, imax), (2.0, 7), (inf, imax), (0.0, 3)]
        running = [(1.0, 50), (inf, imax), (inf, imax)]
        check_case(4, 3, fresh, running)

    def test_duplicate_distances_prefer_low_index(self):
        fresh = [(1.0, 9), (1.0, 2), (1.0, 5)]
        running = [(1.0, 0), (1.0, 7)]
        got = run_program(3, 2, fresh, running)
        assert got == [(1.0, 0), (1.0, 2)]

    def test_cost_routing(self):
        # The kernel routes by program_cost < rounds_cost. With the r5
        # resolved tie modes the network undercuts the rounds at EVERY
        # bench shape including k <= 2 (device-confirmed on the headline
        # shape: k=1 net 0.476 vs rounds 0.527 ms, k=2 0.552 vs 0.595,
        # k=5 0.655 vs 0.869, k=10 0.832 vs 2.859 — r5 interleaved
        # medians). The rounds formulation stays as the select="rounds"
        # probe baseline and the non-finite fallback comparison point.
        from knn_tpu.ops.topk_net import rounds_cost

        for g, k in ((8, 5), (16, 5), (96, 10), (16, 16), (8, 3), (16, 4),
                     (8, 1), (16, 2), (96, 2)):
            for finite in (False, True):
                ops, _ = tile_topk_program(g, k, finite)
                assert program_cost(ops) < rounds_cost(g, k), (g, k, finite)
        # The finite variant is never costlier than the non-finite one.
        for g, k in ((16, 5), (96, 10), (16, 16)):
            base = program_cost(tile_topk_program(g, k, False)[0])
            fin = program_cost(tile_topk_program(g, k, True)[0])
            assert fin <= base, (g, k, fin, base)

    def test_outputs_sorted_invariant(self):
        # The out wires must be sorted so the next tile's merge sees a
        # sorted running list — the invariant the whole tournament rests on.
        rng = random.Random(1)
        for _ in range(100):
            g, k = rng.randint(1, 20), rng.randint(1, 8)
            fresh = [(rng.random(), i) for i in range(g)]
            running = sorted((rng.random(), 100 + i) for i in range(k))
            got = run_program(g, k, fresh, running)
            assert got == sorted(got)
