"""Correctness of the compile-time top-k merge networks (ops/topk_net.py).

The programs are pure data; these tests validate them on host scalars. The
0-1 principle (Knuth 5.3.4) makes the small exhaustive sweeps PROOFS for
every (g, k) they cover: a comparator network computes the sorted top-k of
every input iff it does so for every 0-1 input, and truncation is covered
because the tested property is end-to-end (output == sorted(all inputs)[:k]).
"""

import itertools
import random

from knn_tpu.ops.topk_net import program_cost, simulate, tile_topk_program


def run_program(g, k, fresh_vals, running_vals):
    ops, out = tile_topk_program(g, k)
    vals = list(fresh_vals) + sorted(running_vals)
    result = simulate(ops, vals)
    return [result[w] for w in out]


def check_case(g, k, fresh_vals, running_vals):
    got = run_program(g, k, fresh_vals, running_vals)
    want = sorted(list(fresh_vals) + list(running_vals))[:k]
    assert got == want, (g, k, fresh_vals, running_vals, got, want)


class TestTileTopkProgram:
    def test_zero_one_exhaustive_small(self):
        # Every 0-1 assignment of the g fresh + k running wires (running
        # sorted, as the kernel invariant guarantees) for every small shape:
        # by the 0-1 principle this proves the network for these (g, k).
        for g in range(1, 9):
            for k in range(1, 6):
                for bits in itertools.product((0, 1), repeat=g):
                    for ones in range(k + 1):
                        fresh = [(b, i) for i, b in enumerate(bits)]
                        running = [
                            (0 if i < k - ones else 1, 100 + i) for i in range(k)
                        ]
                        check_case(g, k, fresh, running)

    def test_zero_one_exhaustive_bench_shapes(self):
        # The bench shapes are too wide for full exhaustion; exhaust the 0-1
        # patterns of a sliding window of fresh wires (others pinned) plus
        # every running fill level — covers every comparator the window
        # touches. g=16 (headline block_n=2048), g=8 (mnist block_n=1024),
        # g=96 (xl block_n=12288, k=10).
        for g, k in ((16, 5), (8, 5), (96, 10)):
            for lo in range(0, g - 3, 3):
                for bits in itertools.product((0, 1), repeat=4):
                    fresh = [(1, i) for i in range(g)]
                    for off, b in enumerate(bits):
                        fresh[lo + off] = (b, lo + off)
                    for ones in (0, k // 2, k):
                        running = [
                            (0 if i < k - ones else 1, 1000 + i)
                            for i in range(k)
                        ]
                        check_case(g, k, fresh, running)

    def test_random_with_heavy_ties(self):
        # Lexicographic (d, i) semantics under dense ties: the kept set and
        # its order must match a stable host sort — first-seen-wins on equal
        # distances (main.cpp:47).
        rng = random.Random(0)
        for _ in range(400):
            g = rng.randint(1, 24)
            k = rng.randint(1, 10)
            fresh = [(rng.randint(0, 3), i) for i in range(g)]
            running = [(rng.randint(0, 3), 100 + i) for i in range(k)]
            check_case(g, k, fresh, running)

    def test_inf_padding_flows(self):
        # +inf/INT_MAX padding (masked lanes, init levels) must lose to any
        # finite candidate and tie harmlessly among themselves.
        inf = float("inf")
        imax = 2**31 - 1
        fresh = [(inf, imax), (2.0, 7), (inf, imax), (0.0, 3)]
        running = [(1.0, 50), (inf, imax), (inf, imax)]
        check_case(4, 3, fresh, running)

    def test_duplicate_distances_prefer_low_index(self):
        fresh = [(1.0, 9), (1.0, 2), (1.0, 5)]
        running = [(1.0, 0), (1.0, 7)]
        got = run_program(3, 2, fresh, running)
        assert got == [(1.0, 0), (1.0, 2)]

    def test_cost_routing(self):
        # The reason this module exists: the network must beat the k-round
        # min-extraction on the shapes the kernel routes to it (every
        # bench-relevant k >= 3 shape), and the kernel's routing rule
        # (program_cost < rounds_cost) must keep the rounds at k <= 2 where
        # two thin passes beat fused (d, i) comparators.
        from knn_tpu.ops.topk_net import rounds_cost

        for g, k in ((8, 5), (16, 5), (96, 10), (16, 16), (8, 3), (16, 4)):
            ops, _ = tile_topk_program(g, k)
            assert program_cost(ops) < rounds_cost(g, k), (g, k)
        for g, k in ((8, 1), (16, 2), (96, 2)):
            ops, _ = tile_topk_program(g, k)
            assert program_cost(ops) >= rounds_cost(g, k), (g, k)

    def test_outputs_sorted_invariant(self):
        # The out wires must be sorted so the next tile's merge sees a
        # sorted running list — the invariant the whole tournament rests on.
        rng = random.Random(1)
        for _ in range(100):
            g, k = rng.randint(1, 20), rng.randint(1, 8)
            fresh = [(rng.random(), i) for i in range(g)]
            running = sorted((rng.random(), 100 + i) for i in range(k))
            got = run_program(g, k, fresh, running)
            assert got == sorted(got)
