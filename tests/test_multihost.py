"""Multi-process (multi-controller) distributed path — the MPI replacement,
tested the way the reference never could be: an actual 2-process run over a
coordinator, exercising jax.distributed init, a global mesh spanning both
processes' devices, shard_map scatter/compute, and the replicating
all-gather (SURVEY.md §4 called multi-node testing out as absent upstream).
"""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from tests import fixtures

REPO = Path(__file__).resolve().parent.parent

# Older jaxlib CPU clients (e.g. 0.4.37) cannot run cross-process
# collectives at all — the worker dies with this exact runtime error. That
# is an environment limitation, not a regression in the launch path, so the
# tier-1 gate skips rather than fails on it.
_CPU_MULTIPROC_UNSUPPORTED = "Multiprocess computations aren't implemented"


def _skip_if_cpu_multiprocess_unsupported(proc):
    if proc.returncode != 0 and _CPU_MULTIPROC_UNSUPPORTED in proc.stderr:
        pytest.skip(
            "this jaxlib's CPU backend does not implement multi-process "
            "collectives"
        )


def test_two_process_launch_matches_oracle(tmp_path):
    from knn_tpu.backends.oracle import knn_oracle
    from knn_tpu.data.arff import load_arff

    datasets = fixtures.datasets_dir()  # reference checkout or synth fallback
    dump = tmp_path / "preds.npy"
    proc = subprocess.run(
        [
            sys.executable, "scripts/launch_multihost.py",
            "-np", "2", "--devices-per-proc", "2",
            str(datasets / "small-train.arff"),
            str(datasets / "small-test.arff"),
            "5", "--dump-predictions", str(dump),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=240,
    )
    _skip_if_cpu_multiprocess_unsupported(proc)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "Accuracy was" in proc.stdout
    if fixtures.using_reference_datasets():
        assert "Accuracy was 0.8625" in proc.stdout  # golden, BASELINE.md

    train = load_arff(str(datasets / "small-train.arff"))
    test = load_arff(str(datasets / "small-test.arff"))
    want = knn_oracle(
        train.features, train.labels, test.features, 5, train.num_classes
    )
    got = np.load(dump)
    np.testing.assert_array_equal(got, want)


def test_two_process_train_sharded_matches_oracle(tmp_path):
    # --strategy train-sharded: the global mesh scatters TRAIN rows (the
    # index that does not fit one device) instead of queries; per-shard
    # top-k all-gathered and lexicographically merged — the serve tier's
    # shard/plan partition under the real launcher (VERDICT seam #1's
    # train-sharded half).
    from knn_tpu.backends.oracle import knn_oracle
    from knn_tpu.data.arff import load_arff

    datasets = fixtures.datasets_dir()
    dump = tmp_path / "preds.npy"
    proc = subprocess.run(
        [
            sys.executable, "scripts/launch_multihost.py",
            "-np", "2", "--devices-per-proc", "2",
            str(datasets / "small-train.arff"),
            str(datasets / "small-test.arff"),
            "5", "--strategy", "train-sharded",
            "--dump-predictions", str(dump),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=240,
    )
    _skip_if_cpu_multiprocess_unsupported(proc)
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "Accuracy was" in proc.stdout
    train = load_arff(str(datasets / "small-train.arff"))
    test = load_arff(str(datasets / "small-test.arff"))
    want = knn_oracle(
        train.features, train.labels, test.features, 5, train.num_classes
    )
    np.testing.assert_array_equal(np.load(dump), want)


def test_train_sharded_stripe_engine_is_a_usage_error():
    # No coordinator needed: the contradiction is rejected before any
    # backend touch, with the serve exit-code contract (2 = usage).
    proc = subprocess.run(
        [
            sys.executable, "-m", "knn_tpu.parallel.multihost",
            "train.arff", "test.arff", "5",
            "--strategy", "train-sharded", "--engine", "stripe",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=60,
    )
    assert proc.returncode == 2, proc.stderr[-500:]
    assert "xla engine only" in proc.stderr


def test_two_process_stripe_engine_matches_oracle(tmp_path):
    # The same 2-process launch forced through the lane-striped Pallas
    # engine (interpret mode on the CPU processes): the full mpiexec
    # replacement riding the single-chip headline kernel (VERDICT r1 #1
    # extended to multi-controller).
    from knn_tpu.backends.oracle import knn_oracle
    from knn_tpu.data.arff import load_arff

    datasets = fixtures.datasets_dir()
    dump = tmp_path / "preds.npy"
    proc = subprocess.run(
        [
            sys.executable, "scripts/launch_multihost.py",
            "-np", "2", "--devices-per-proc", "2",
            str(datasets / "small-train.arff"),
            str(datasets / "small-test.arff"),
            "5", "--engine", "stripe", "--dump-predictions", str(dump),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=240,
    )
    _skip_if_cpu_multiprocess_unsupported(proc)
    assert proc.returncode == 0, proc.stderr[-2000:]
    train = load_arff(str(datasets / "small-train.arff"))
    test = load_arff(str(datasets / "small-test.arff"))
    want = knn_oracle(
        train.features, train.labels, test.features, 5, train.num_classes
    )
    np.testing.assert_array_equal(np.load(dump), want)
