"""Device-side observability (knn_tpu/obs/devprof.py): memory gauges,
compile-event counters, executable-cache hit/miss, profiler capture
sessions, and the serve endpoints that surface them (ISSUE 6 acceptance).
"""

from __future__ import annotations

import json
import threading
import urllib.request

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.obs import devprof


@pytest.fixture()
def global_obs():
    obs.reset()
    obs.enable()
    yield obs
    obs.disable()
    obs.reset()


class _FakeDevice:
    """A device whose memory_stats() reports allocator numbers."""

    platform = "faketpu"
    id = 7

    def memory_stats(self):
        return {"bytes_in_use": 1234, "peak_bytes_in_use": 9999}


class _FakeBareDevice:
    """A device with no memory_stats and no client — the deepest fallback."""

    platform = "bare"
    id = 0

    def memory_stats(self):
        return None


class TestDeviceMemory:
    def test_memory_stats_device(self, global_obs):
        stats = devprof.record_device_memory(devices=[_FakeDevice()])
        assert stats == [{
            "device": "faketpu:7", "platform": "faketpu",
            "in_use": 1234, "peak": 9999, "source": "memory_stats",
        }]
        gauges = {
            (dict(i.labels)["kind"]): i.value
            for i in obs.registry().instruments()
            if i.name == "knn_device_memory_bytes"
        }
        assert gauges == {"in_use": 1234, "peak": 9999}

    def test_bare_device_falls_back_to_zero(self, global_obs):
        stats = devprof.device_memory_stats(devices=[_FakeBareDevice()])
        assert stats[0]["source"] == "live_buffers"
        assert stats[0]["in_use"] == 0

    def test_real_cpu_device_live_buffer_fallback(self, global_obs):
        # CPU jaxlib reports no memory_stats; the fallback sums live
        # buffers — hold one so in_use is non-zero and peak tracks it.
        import jax.numpy as jnp

        buf = jnp.ones((256, 256), jnp.float32)
        buf.block_until_ready()
        stats = devprof.record_device_memory()
        mine = stats[0]
        assert mine["source"] in ("memory_stats", "live_buffers")
        assert mine["in_use"] >= buf.nbytes
        assert mine["peak"] >= mine["in_use"]
        del buf

    def test_disabled_records_nothing(self):
        assert not obs.enabled()
        obs.reset()
        devprof.record_device_memory(devices=[_FakeDevice()])
        assert obs.registry().instruments() == []


class TestCompileEvents:
    def test_fresh_compile_records_events_and_walls(self, global_obs):
        import jax
        import jax.numpy as jnp

        # A shape no other test uses: guarantees a fresh compilation.
        jax.jit(lambda x: x @ x + 41)(jnp.ones((41, 41))).block_until_ready()
        summary = devprof.compile_summary()
        assert "backend_compile" in summary
        assert summary["backend_compile"]["count"] >= 1
        assert summary["backend_compile"]["wall_ms_total"] > 0
        names = {i.name for i in obs.registry().instruments()}
        assert "knn_compile_events_total" in names
        assert "knn_compile_wall_ms" in names

    def test_disabled_listener_records_nothing(self):
        assert not obs.enabled()
        devprof.install_compile_listeners()
        obs.reset()
        import jax
        import jax.numpy as jnp

        jax.jit(lambda x: x @ x + 43)(jnp.ones((43, 43))).block_until_ready()
        assert obs.registry().instruments() == []

    def test_timed_compile_records_explicit_wall(self, global_obs):
        import jax
        import jax.numpy as jnp

        fn = jax.jit(lambda x: x * 2 + 47)
        compiled = devprof.timed_compile(fn, jnp.ones((47,)), label="probe")
        assert compiled is not None
        gauges = [i for i in obs.registry().instruments()
                  if i.name == "knn_compile_explicit_wall_ms"]
        assert len(gauges) == 1 and gauges[0].value > 0
        assert dict(gauges[0].labels)["label"] == "probe"


class TestExecutableCache:
    def test_miss_then_hit(self, global_obs):
        assert devprof.record_executable_lookup("b", ("sig", 1)) == "miss"
        assert devprof.record_executable_lookup("b", ("sig", 1)) == "hit"
        assert devprof.record_executable_lookup("b", ("sig", 2)) == "miss"
        assert devprof.executable_cache_summary() == {"hits": 1, "misses": 2}

    def test_reset_clears_signatures(self, global_obs):
        devprof.record_executable_lookup("b", ("sig",))
        obs.reset()
        obs.enable()
        assert devprof.record_executable_lookup("b", ("sig",)) == "miss"

    def test_off_records_nothing(self):
        assert not obs.enabled()
        obs.reset()
        assert devprof.record_executable_lookup("b", ("x",)) == "off"
        assert obs.registry().instruments() == []

    def test_predict_path_records_lookup(self, global_obs, small):
        from knn_tpu.models.knn import KNNClassifier

        train, test = small
        model = KNNClassifier(k=3, backend="tpu", engine="xla").fit(train)
        model.predict(test)
        model.predict(test)
        summary = devprof.executable_cache_summary()
        assert summary["misses"] >= 1
        assert summary["hits"] >= 1


class TestCapture:
    def test_capture_produces_nonempty_trace_with_both_kinds(
        self, global_obs
    ):
        import jax
        import jax.numpy as jnp

        with devprof.capture() as cap:
            with obs.span("serve.dispatch", probe=1):
                jax.jit(lambda x: x @ x)(
                    jnp.ones((53, 53))
                ).block_until_ready()
        trace = cap.trace
        assert cap.error is None
        assert trace["traceEvents"], "capture produced an empty trace"
        names = {e.get("name", "") for e in trace["traceEvents"]
                 if isinstance(e, dict)}
        # The host span rode the TraceAnnotation pass-through into the
        # device timeline, next to real device-side events.
        assert "serve.dispatch" in names
        assert any("Execute" in n or n.startswith("dot") for n in names)
        # The pass-through was scoped to the window.
        assert obs.tracer().jax_annotations is False

    def test_concurrent_capture_raises_busy(self, global_obs):
        with devprof.capture():
            with pytest.raises(devprof.CaptureBusy):
                with devprof.capture():
                    pass

    def test_capture_counts_outcome(self, global_obs):
        with devprof.capture():
            pass
        counters = [i for i in obs.registry().instruments()
                    if i.name == "knn_profile_captures_total"]
        assert counters and counters[0].value >= 1


class TestServeEndpoints:
    """The ISSUE 6 acceptance pins: /debug/profile under load returns a
    Perfetto-loadable trace with serve spans AND device events;
    knn_device_memory_bytes is in /metrics and /healthz carries the
    device block."""

    @pytest.fixture(scope="class")
    def server(self, small):
        from knn_tpu.models.knn import KNNClassifier
        from knn_tpu.serve.server import ServeApp, make_server

        train, _ = small
        obs.reset()
        obs.enable()
        model = KNNClassifier(k=3).fit(train)
        app = ServeApp(model, max_batch=8, max_wait_ms=1.0)
        server = make_server(app)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        app.warm((1, 8))
        host, port = server.server_address[:2]
        yield f"http://{host}:{port}", app, train
        server.shutdown()
        app.close()
        obs.disable()
        obs.reset()

    def _get(self, url, timeout=120):
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, r.read().decode()

    def test_metrics_carry_device_memory(self, server):
        base, _, _ = server
        st, body = self._get(base + "/metrics")
        assert st == 200
        assert "knn_device_memory_bytes" in body

    def test_healthz_device_block(self, server):
        base, _, _ = server
        st, body = self._get(base + "/healthz")
        h = json.loads(body)
        assert st == 200
        dev = h["device"]
        assert dev["memory"] and "in_use" in dev["memory"][0]
        assert set(dev["executable_cache"]) == {"hits", "misses"}
        assert isinstance(dev["compile"], dict)

    def test_debug_profile_under_load(self, server):
        base, _, train = server
        rows = train.features[:2].tolist()
        stop = threading.Event()

        def load():
            req = urllib.request.Request(
                base + "/predict",
                data=json.dumps({"instances": rows}).encode(),
                headers={"Content-Type": "application/json"},
            )
            while not stop.is_set():
                try:
                    urllib.request.urlopen(req, timeout=30).read()
                except Exception:  # noqa: BLE001 — load gen best-effort
                    pass

        loader = threading.Thread(target=load, daemon=True)
        loader.start()
        try:
            st, body = self._get(base + "/debug/profile?ms=150")
        finally:
            stop.set()
            loader.join(timeout=10)
        assert st == 200
        trace = json.loads(body)
        events = trace["traceEvents"]
        assert events
        names = {e.get("name", "") for e in events if isinstance(e, dict)}
        if trace["otherData"].get("source") == "jax.profiler":
            assert any(n.startswith("serve.") for n in names), \
                "no serve host spans in the captured device timeline"
            assert any("Execute" in n for n in names), \
                "no device-side events in the capture"

    def test_debug_profile_validation(self, server):
        base, _, _ = server
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as e:
            self._get(base + "/debug/profile?ms=banana")
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            self._get(base + f"/debug/profile?ms={devprof.MAX_CAPTURE_MS + 1}")
        assert e.value.code == 400


class TestCliProfileOut:
    @pytest.fixture(autouse=True)
    def _clean_global_state(self):
        # run() restores the enabled flag but (by design) leaves the run's
        # instruments in the global registry; drop them so the
        # disabled-is-noop pins elsewhere see a clean slate.
        yield
        obs.disable()
        obs.reset()

    def test_classify_writes_perfetto_trace(self, tmp_path, small_paths):
        from knn_tpu.cli import run

        train_p, test_p = small_paths
        out = tmp_path / "profile.json"
        rc = run([train_p, test_p, "3", "--backend", "oracle",
                  "--profile-out", str(out)])
        assert rc == 0
        trace = json.loads(out.read_text())
        assert trace["traceEvents"]
        names = {e.get("name", "") for e in trace["traceEvents"]
                 if isinstance(e, dict)}
        if trace["otherData"].get("source") == "jax.profiler":
            assert "classify" in names  # host span inside the device trace

    def test_unwritable_profile_out_exits_2(self, small_paths):
        from knn_tpu.cli import run

        train_p, test_p = small_paths
        rc = run([train_p, test_p, "3", "--backend", "oracle",
                  "--profile-out", "/nonexistent-dir/profile.json"])
        assert rc == 2
