"""Collective-bytes audit (VERDICT r4 #8): the distributed paths' lowered
programs must communicate exactly what the wire model says — three
all-gathers of [q_local, k*P] 4-byte triples for train sharding, one
(shard, labels) collective_permute per ring step — and the audit must
reject lowerings that do anything else.
"""

import numpy as np
import pytest
import jax.numpy as jnp

from knn_tpu.parallel.comm_audit import (
    audit_ring, audit_train_sharded, collective_ops,
)
from knn_tpu.parallel.mesh import make_mesh, make_mesh_2d
from knn_tpu.parallel.ring import build_ring_fn
from knn_tpu.parallel.train_sharded import build_train_sharded_fn
from knn_tpu.utils.padding import pad_axis_to_multiple


@pytest.fixture(scope="module")
def toy():
    rng = np.random.default_rng(0)
    train_x = rng.random((512, 8), np.float32)
    train_y = rng.integers(0, 10, 512).astype(np.int32)
    test_x = rng.random((64, 8), np.float32)
    return train_x, train_y, test_x


def test_train_sharded_collectives_match_model(toy):
    train_x, train_y, test_x = toy
    n_q, n_t, k, qt, tt = 2, 4, 5, 8, 32
    fn = build_train_sharded_fn(
        make_mesh_2d(n_q, n_t), k, 10, "exact", query_tile=qt, train_tile=tt,
    )
    txt = fn.lower(
        jnp.asarray(train_x), jnp.asarray(train_y), jnp.asarray(test_x),
        jnp.asarray(512, jnp.int32),
    ).as_text()
    q_local = test_x.shape[0] // n_q
    measured, expected = audit_train_sharded(txt, q_local, k, n_t)
    assert measured == expected == q_local * k * n_t * 12


def test_ring_collectives_match_model(toy):
    train_x, train_y, test_x = toy
    n_dev = 8
    fn = build_ring_fn(make_mesh(n_dev, axis_names=("r",)), 5, 10, "exact",
                       engine="full")
    txt = fn.lower(
        jnp.asarray(train_x), jnp.asarray(train_y), jnp.asarray(test_x),
        jnp.asarray(512, jnp.int32),
    ).as_text()
    shard = train_x.shape[0] // n_dev
    measured, expected = audit_ring(txt, shard * 8 * 4, shard * 4, n_dev)
    assert measured == expected == (shard * 8 * 4 + shard * 4) * (n_dev - 1)


def test_ring_stripe_collectives(toy):
    # The stripe-engine ring permutes the TRANSPOSED shard — same bytes.
    from knn_tpu.ops.pallas_knn import stripe_prepare_sharded

    train_x, train_y, test_x = toy
    n_dev = 4
    txT, ty, qx, block_q, block_n = stripe_prepare_sharded(
        train_x, train_y, test_x, 5, n_dev, n_dev,
    )
    fn = build_ring_fn(
        make_mesh(n_dev, axis_names=("r",)), 5, 10, "exact", engine="stripe",
        block_q=block_q, block_n=block_n, d_true=train_x.shape[1],
        interpret=True,
    )
    txt = fn.lower(
        jnp.asarray(txT), jnp.asarray(ty), jnp.asarray(qx),
        jnp.asarray(512, jnp.int32),
    ).as_text()
    shard_cols = txT.shape[1] // n_dev
    measured, expected = audit_ring(
        txt, txT.shape[0] * shard_cols * 4, shard_cols * 4, n_dev,
    )
    assert measured == expected


def test_audit_rejects_wrong_model(toy):
    train_x, train_y, test_x = toy
    fn = build_train_sharded_fn(
        make_mesh_2d(2, 4), 5, 10, "exact", query_tile=8, train_tile=32,
    )
    txt = fn.lower(
        jnp.asarray(train_x), jnp.asarray(train_y), jnp.asarray(test_x),
        jnp.asarray(512, jnp.int32),
    ).as_text()
    with pytest.raises(AssertionError, match="shape"):
        audit_train_sharded(txt, q_local=99, k=5, n_t=4)
    with pytest.raises(AssertionError, match="unexpected collectives"):
        audit_ring(txt, 1, 1, 4)  # all-gathers are not a ring program


def test_parser_reads_shapes_and_dtypes():
    txt = (
        '%19 = "stablehlo.all_gather"(%16) <{...}> : '
        "(tensor<8x5xf32>) -> tensor<8x40xf32>\n"
        '%0 = "stablehlo.collective_permute"(%arg3) <{...}> : '
        "(tensor<64x8xi32>) -> tensor<64x8xi32>\n"
    )
    ops = collective_ops(txt)
    assert ops == [
        ("all_gather", (8, 40), "f32", 8 * 40 * 4),
        ("collective_permute", (64, 8), "i32", 64 * 8 * 4),
    ]
