"""Workload capture / replay / what-if contract tests
(docs/OBSERVABILITY.md §Workload capture & replay).

The enforced promises: the workload artifact round-trips exactly and
refuses corruption typed (DataError, like serve/artifact.py); the
capture tap sheds under overload and NEVER blocks the producer (the
ShedQueue contract); the burn trigger arms a window on SLO burn and the
window auto-finalizes; replay is deterministic — a capture replayed
against the same serving state verifies bit-identical, twice; the
what-if simulator reproduces the batcher's coalescing rules exactly on
hand-computable schedules; and the access-log/flight-recorder linkage
carries the workload record id.
"""

import json
import time

import numpy as np
import pytest

from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.obs import whatif
from knn_tpu.obs.replay import replay_workload
from knn_tpu.obs.reqtrace import FlightRecorder
from knn_tpu.obs.workload import (
    CaptureStateError,
    WorkloadCapture,
    answer_digest,
    load_workload,
)
from knn_tpu.resilience.errors import DataError
from knn_tpu.serve.batcher import MicroBatcher

D = 6


@pytest.fixture(scope="module")
def model():
    rng = np.random.default_rng(7)
    train = Dataset(rng.normal(0, 2, (160, D)).astype(np.float32),
                    rng.integers(0, 4, 160).astype(np.int32))
    return KNNClassifier(k=3).fit(train)


def _capture_some(model, tmp_path, n=12, version="v1", rate=1.0):
    rng = np.random.default_rng(3)
    cap = WorkloadCapture(tmp_path / "captures", num_features=D, k=3,
                          rate=rate, policy={"max_batch": 8,
                                             "max_wait_ms": 0.5})
    batcher = MicroBatcher(model, max_batch=8, max_wait_ms=0.5,
                           index_version=version, workload=cap)
    try:
        cap.start()
        futures = []
        for i in range(n):
            q = rng.normal(0, 2, (int(rng.integers(1, 4)), D)) \
                .astype(np.float32)
            kind = "kneighbors" if i % 4 == 0 else "predict"
            futures.append(batcher.submit(q, kind))
            time.sleep(0.002)
        for f in futures:
            f.result(timeout=30)
        assert cap.drain(20)
        summary = cap.stop()
    finally:
        batcher.close()
        cap.close()
    return summary


class TestArtifactRoundTrip:
    def test_round_trip(self, model, tmp_path):
        summary = _capture_some(model, tmp_path, n=12)
        assert summary["requests"] == 12
        wl = load_workload(summary["path"])
        assert wl.manifest["format"] == 1
        assert wl.manifest["num_features"] == D
        assert wl.manifest["policy"]["max_batch"] == 8
        assert len(wl.read_events) == 12
        assert wl.rows.dtype == np.float32
        assert wl.rows.shape[1] == D
        # Events are sorted by arrival time and fully described.
        t_last = -1.0
        total = 0
        for ev in wl.read_events:
            assert ev["t_ms"] >= t_last
            t_last = ev["t_ms"]
            assert ev["outcome"] == "ok"
            assert ev["rung"] == "fast"
            assert ev["index_version"] == "v1"
            assert ev["digest"]
            assert ev["ms"] > 0
            block = wl.rows_for(ev)
            assert block.shape == (ev["rows"], D)
            total += ev["rows"]
        assert total == wl.manifest["total_rows"]
        # The digest is transport-canonical: recomputing from a float64
        # JSON round trip of the captured rows' answers matches.
        preds = model.predict(
            Dataset(wl.rows_for(wl.read_events[1]),
                    np.zeros(wl.read_events[1]["rows"], np.int32)))
        again = np.asarray(json.loads(json.dumps(
            np.asarray(preds, np.float64).tolist())))
        if wl.read_events[1]["kind"] == "predict":
            assert answer_digest("predict", again) == \
                wl.read_events[1]["digest"]

    def test_arrivals_and_summary(self, model, tmp_path):
        wl = load_workload(_capture_some(model, tmp_path, n=6)["path"])
        arr = wl.arrivals()
        assert len(arr) == 6
        assert all(r >= 1 for _t, r in arr)
        s = wl.captured_latency_summary()
        assert s["requests"] == 6 and s["ok"] == 6
        assert s["p50_ms"] > 0


class TestCorruptionRefusal:
    @pytest.fixture
    def artifact_dir(self, model, tmp_path):
        from pathlib import Path

        return Path(_capture_some(model, tmp_path, n=4)["path"])

    def test_missing_dir_typed(self, tmp_path):
        with pytest.raises(DataError, match="not found"):
            load_workload(tmp_path / "nope")

    def test_not_an_artifact_typed(self, tmp_path):
        (tmp_path / "junk").mkdir()
        with pytest.raises(DataError, match="not a workload artifact"):
            load_workload(tmp_path / "junk")

    def test_newer_format_refused(self, artifact_dir):
        mf = json.loads((artifact_dir / "manifest.json").read_text())
        mf["format"] = 99
        (artifact_dir / "manifest.json").write_text(json.dumps(mf))
        with pytest.raises(DataError, match="newer"):
            load_workload(artifact_dir)

    def test_edited_manifest_refused(self, artifact_dir):
        mf = json.loads((artifact_dir / "manifest.json").read_text())
        mf["num_features"] = D + 1  # schema lie
        (artifact_dir / "manifest.json").write_text(json.dumps(mf))
        with pytest.raises(DataError, match="schema hash"):
            load_workload(artifact_dir)

    def test_tampered_events_refused(self, artifact_dir):
        p = artifact_dir / "events.jsonl"
        text = p.read_text()
        p.write_text(text.replace('"outcome":"ok"', '"outcome":"no"', 1))
        with pytest.raises(DataError, match="schema hash"):
            load_workload(artifact_dir)

    def test_truncated_queries_refused(self, artifact_dir):
        p = artifact_dir / "queries.npz"
        p.write_bytes(p.read_bytes()[:40])
        with pytest.raises(DataError):
            load_workload(artifact_dir)


class TestShedNeverBlocks:
    def test_full_queue_sheds_fast(self, tmp_path):
        # Consumer held off: every offer past the cap must shed in O(1),
        # never block the producer (the serving worker thread).
        cap = WorkloadCapture(tmp_path, num_features=D, queue_cap=4,
                              autostart=False)
        cap.start()

        class FakeReq:
            kind = "predict"
            rows = 1
            deadline_ns = None
            request_class = None
            trace = None
            meta: dict = {}
            features = np.zeros((1, D), np.float32)
            value = None

            def __init__(self):
                self.enqueued_ns = time.monotonic_ns()

        t0 = time.monotonic()
        captured = sum(
            1 for _ in range(200)
            if cap.note_request(FakeReq(), "ok") is not None
        )
        elapsed = time.monotonic() - t0
        assert captured == 4  # the queue cap; everything else shed
        status = cap.export()
        assert status["shed"] == 196
        assert elapsed < 1.0  # 200 offers, no blocking anywhere
        cap._queue.start()  # let close() drain cleanly
        cap.close()

    def test_mutation_shed_marks_stream_incomplete(self, tmp_path):
        cap = WorkloadCapture(tmp_path, num_features=D, queue_cap=1,
                              autostart=False)
        cap.start()
        for _ in range(3):
            cap.note_mutation("delete", {"ids": [1]}, seq=1,
                              enqueued_ns=time.monotonic_ns())
        cap._queue.start()
        assert cap.drain(10)
        summary = cap.stop()
        cap.close()
        wl = load_workload(summary["path"])
        assert wl.manifest["mutations"] == 1
        assert wl.manifest["mutation_stream_complete"] is False


class TestBurnTrigger:
    def test_burn_arms_and_window_finalizes(self, model, tmp_path):
        from knn_tpu.obs.slo import SLOTracker

        slo = SLOTracker(windows_s=(1, 2))
        cap = WorkloadCapture(
            tmp_path, num_features=D, slo=slo, burn_threshold=2.0,
            burn_objective="availability", burn_window_s=0.05,
            burn_check_interval_s=0.0,
        )
        batcher = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                               index_version="v1", workload=cap)
        try:
            # Healthy traffic: no arming.
            batcher.predict(np.zeros(D, np.float32), timeout=30)
            assert cap.capturing is False
            # Burn the availability budget hard, then serve again: the
            # tap's throttled check sees burn >> threshold and arms.
            for _ in range(50):
                slo.record(False, 1.0)
            batcher.predict(np.zeros(D, np.float32), timeout=30)
            assert cap.capturing is True
            status = cap.export()
            assert status["reason"] == "burn:availability"
            # One request INSIDE the window (the arming request itself
            # predates t0 and is excluded by design).
            batcher.predict(np.zeros(D, np.float32), timeout=30)
            # Past the window: the next tap flags the stop and a status
            # read completes the deferred finalization.
            time.sleep(0.08)
            batcher.predict(np.zeros(D, np.float32), timeout=30)
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                status = cap.export()
                if not status["capturing"] and status["last"]:
                    break
                time.sleep(0.01)
            assert status["capturing"] is False
            assert status["last"]["reason"] == "burn:availability"
            assert status["last"]["stop_reason"] == "window_elapsed"
            assert status["last"]["requests"] >= 1
            load_workload(status["last"]["path"])  # validates
        finally:
            batcher.close()
            cap.close()

    def test_timed_window_finalizes_without_traffic(self, tmp_path):
        # Traffic ceases after arming (the zero-traffic incident tail):
        # no tap ever sees the deadline pass, so the next status read —
        # /healthz, /metrics, /debug/capture all route here — must
        # expire the window and write the artifact.
        cap = WorkloadCapture(tmp_path, num_features=D)
        cap.start(window_s=0.02)
        time.sleep(0.05)
        status = cap.export()
        assert status["capturing"] is False
        assert status["last"] is not None
        assert status["last"]["stop_reason"] == "window_elapsed"
        load_workload(status["last"]["path"])  # validates
        cap.close()

    def test_record_ids_monotonic_across_windows(self, model, tmp_path):
        # A workload_record annotation names one record process-wide:
        # ids must not reset per window.
        s1 = _capture_some(model, tmp_path, n=3)
        s2 = _capture_some(model, tmp_path / "w2", n=3)
        wl1 = load_workload(s1["path"])
        wl2 = load_workload(s2["path"])
        assert {e["id"] for e in wl1.events} == {0, 1, 2}
        # Different capture instance -> fresh counter is fine; SAME
        # instance across two windows must continue counting.
        cap = WorkloadCapture(tmp_path / "w3", num_features=D)
        b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                         workload=cap)
        try:
            cap.start()
            b.predict(np.zeros(D, np.float32), timeout=30)
            assert cap.drain(20)
            cap.stop()
            cap.start()
            b.predict(np.zeros(D, np.float32), timeout=30)
            assert cap.drain(20)
            second = cap.stop()
        finally:
            b.close()
            cap.close()
        wl3 = load_workload(second["path"])
        assert wl3.events[0]["id"] == 1  # continued, not reset
        assert wl2.events  # both artifacts loadable

    def test_start_stop_state_errors(self, tmp_path):
        cap = WorkloadCapture(tmp_path, num_features=D)
        with pytest.raises(CaptureStateError):
            cap.stop()
        cap.start()
        with pytest.raises(CaptureStateError):
            cap.start()
        cap.stop()
        cap.close()


class TestReplayDeterminism:
    def test_capture_replays_bit_identical_twice(self, model, tmp_path):
        wl = load_workload(
            _capture_some(model, tmp_path, n=10, version="vX")["path"])
        for _round in range(2):
            b = MicroBatcher(model, max_batch=8, max_wait_ms=0.5,
                             index_version="vX")
            try:
                v = replay_workload(wl, batcher=b, speed=0.0,
                                    verify="tag")
            finally:
                b.close()
            assert v["measured"]["errors"] == 0
            assert v["verify"]["divergences"] == 0
            assert v["verify"]["verified"] == 10
            assert v["verify"]["skipped_tag_mismatch"] == 0

    def test_version_mismatch_skips_never_diverges(self, model, tmp_path):
        wl = load_workload(
            _capture_some(model, tmp_path, n=5, version="vX")["path"])
        b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                         index_version="OTHER")
        try:
            v = replay_workload(wl, batcher=b, speed=0.0, verify="tag")
        finally:
            b.close()
        assert v["verify"]["skipped_tag_mismatch"] == 5
        assert v["verify"]["divergences"] == 0
        # verify="always" ignores the tag and still matches (same model).
        b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                         index_version="OTHER")
        try:
            v = replay_workload(wl, batcher=b, speed=0.0, verify="always")
        finally:
            b.close()
        assert v["verify"]["verified"] == 5
        assert v["verify"]["divergences"] == 0

    def test_divergence_detected(self, model, tmp_path):
        # A corrupted target (the quality-soak hook) must be CAUGHT: the
        # replay's digests cannot match the capture's.
        wl = load_workload(
            _capture_some(model, tmp_path, n=5, version="vX")["path"])
        b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                         index_version="vX")
        b.corrupt_serving = True
        try:
            v = replay_workload(wl, batcher=b, speed=0.0, verify="tag")
        finally:
            b.close()
        # Every kneighbors answer must diverge (the indices rotated); a
        # predict whose rotated neighbors happen to vote the same label
        # can legitimately still match, so the bound is >=, not ==.
        assert v["verify"]["divergences"] >= 1
        assert v["verify"]["verified"] < 5
        assert v["verify"]["divergence_samples"]

    def test_committed_fixture_replays(self):
        # The committed fixture (bench --config replay rides it): replay
        # mechanics must hold everywhere; digest agreement is asserted
        # only loosely (environment-pinned — see
        # scripts/make_workload_fixture.py).
        from tests import fixtures

        wl = load_workload(fixtures.REPLAY_WORKLOAD_DIR)
        assert wl.manifest["requests"] >= 100
        model = fixtures.replay_fixture_model()
        b = MicroBatcher(model, max_batch=16, max_wait_ms=1.0,
                         index_version=fixtures.REPLAY_FIXTURE_VERSION)
        try:
            v = replay_workload(wl, batcher=b, speed=0.0, verify="tag")
        finally:
            b.close()
        assert v["measured"]["errors"] == 0
        assert v["measured"]["ok"] == wl.manifest["requests"]
        # Tags match by construction (the pinned version string), so
        # every read is either verified or diverged — none skipped.
        assert v["verify"]["skipped_tag_mismatch"] == 0
        assert (v["verify"]["verified"] + v["verify"]["divergences"]
                == wl.manifest["requests"])


class TestMutationReplay:
    def test_mutable_capture_replays_aligned(self, model, tmp_path):
        import shutil

        from knn_tpu.mutable.engine import MutableEngine
        from knn_tpu.serve import artifact

        dir_a, dir_b = tmp_path / "a", tmp_path / "b"
        artifact.save_index(model, dir_a)
        shutil.copytree(dir_a, dir_b)
        version = artifact.index_version(artifact.read_manifest(dir_a))
        rng = np.random.default_rng(11)

        model_a = artifact.load_index(dir_a)
        engine_a = MutableEngine(model_a, dir_a, version=version)
        cap = WorkloadCapture(tmp_path / "captures", num_features=D, k=3)
        b_a = MicroBatcher(model_a, max_batch=8, max_wait_ms=0.0,
                           index_version=version, workload=cap,
                           mutable=engine_a)
        try:
            cap.start()
            futures = []
            for i in range(12):
                if i % 4 == 1:
                    futures.append(b_a.submit_mutation("insert", {
                        "rows": rng.normal(0, 2, (1, D)).astype(np.float32),
                        "values": [int(rng.integers(0, 4))]}))
                elif i == 10:
                    futures.append(b_a.submit_mutation(
                        "delete", {"ids": [model.train_.num_instances]}))
                else:
                    futures.append(b_a.submit(
                        rng.normal(0, 2, (2, D)).astype(np.float32),
                        "predict"))
                for f in futures:
                    f.result(timeout=30)  # serialize: stable seq points
            assert cap.drain(20)
            summary = cap.stop()
        finally:
            b_a.close()
            engine_a.close()
            cap.close()
        assert summary["mutations"] == 4
        wl = load_workload(summary["path"])

        model_b = artifact.load_index(dir_b)
        engine_b = MutableEngine(model_b, dir_b, version=version)
        b_b = MicroBatcher(model_b, max_batch=8, max_wait_ms=0.0,
                           index_version=version, mutable=engine_b)
        try:
            v = replay_workload(wl, batcher=b_b, speed=0.0, verify="tag")
        finally:
            b_b.close()
            engine_b.close()
        assert v["mutations"]["fired"] == 4
        assert v["mutations"]["ok"] == 4
        assert v["mutations"]["seq_aligned"] == 4
        assert v["verify"]["divergences"] == 0
        # Serialized capture -> every read's mutation_seq reproduces.
        assert v["verify"]["verified"] == 8


class TestWhatIfSimulator:
    def test_single_requests_no_coalescing(self):
        # Three lone arrivals, far apart: each dispatches after its own
        # max_wait window, costing a + b*rows.
        sim = whatif.simulate(
            [(0.0, 1), (100.0, 1), (200.0, 1)],
            max_batch=8, max_wait_ms=2.0, a_ms=3.0, b_ms_per_row=0.5,
        )
        assert sim["dispatches"] == 3
        # latency = wait (2.0) + 3.0 + 0.5 = 5.5 for every request
        assert sim["p50_ms"] == pytest.approx(5.5)
        assert sim["p99_ms"] == pytest.approx(5.5)
        assert sim["occupancy_mean"] == pytest.approx(1 / 8)

    def test_batch_closes_at_max_batch(self):
        # 4 rows arrive within the window of the first: the batch closes
        # EARLY at the arrival that reaches max_batch=4 (t=3), not at the
        # window deadline (t=10).
        sim = whatif.simulate(
            [(0.0, 1), (1.0, 1), (2.0, 1), (3.0, 1)],
            max_batch=4, max_wait_ms=10.0, a_ms=2.0, b_ms_per_row=1.0,
        )
        assert sim["dispatches"] == 1
        # close at t=3, wall = 2 + 4 = 6, finish t=9:
        # latencies 9, 8, 7, 6 -> mean 7.5
        assert sim["mean_ms"] == pytest.approx(7.5)
        assert sim["occupancy_mean"] == pytest.approx(1.0)

    def test_busy_worker_coalesces_backlog(self):
        # One slow dispatch; arrivals during it coalesce into the next
        # batch at pickup (window long expired -> no extra wait).
        sim = whatif.simulate(
            [(0.0, 4), (1.0, 1), (2.0, 1)],
            max_batch=4, max_wait_ms=1.0, a_ms=10.0, b_ms_per_row=0.0,
        )
        # batch 1: 4 rows = max_batch, closes immediately at t=0, wall
        # 10, finish 10 -> latency 10. batch 2: picked up at 10 with the
        # window long expired (deadline t=2), dispatches immediately,
        # finish 20 -> latencies 19, 18.
        assert sim["dispatches"] == 2
        assert sim["p50_ms"] == pytest.approx(18.0)
        assert sim["mean_ms"] == pytest.approx((10 + 19 + 18) / 3, abs=0.01)
        assert sim["duty_cycle"] == pytest.approx(1.0, abs=0.01)

    def test_bucket_policy_prices_padding(self):
        # 3-row batch under buckets [4, 8]: padded to 4 -> waste 1/4.
        sim = whatif.simulate(
            [(0.0, 3)], max_batch=8, max_wait_ms=0.0, a_ms=1.0,
            b_ms_per_row=1.0, buckets=[4, 8],
        )
        assert sim["padded_row_waste_ratio"] == pytest.approx(0.25)
        # wall = 1 + 4 (padded rows), not 1 + 3
        assert sim["p50_ms"] == pytest.approx(5.0)

    def test_frontier_shapes(self):
        rows = whatif.frontier(
            [(0.0, 1), (5.0, 1)],
            [{"max_batch": 8, "max_wait_ms": 2.0},
             {"max_batch": 1, "max_wait_ms": 0.0,
              "buckets": [1]}],
            a_ms=1.0, b_ms_per_row=0.1,
        )
        assert len(rows) == 2
        assert rows[0]["policy"]["max_batch"] == 8
        assert rows[1]["p50_ms"] is not None

    def test_validation(self):
        with pytest.raises(ValueError):
            whatif.simulate([], max_batch=0, max_wait_ms=1, a_ms=1,
                            b_ms_per_row=0)
        with pytest.raises(ValueError):
            whatif.simulate([], max_batch=1, max_wait_ms=1, a_ms=-1,
                            b_ms_per_row=0)
        empty = whatif.simulate([], max_batch=1, max_wait_ms=0, a_ms=1,
                                b_ms_per_row=0)
        assert empty["requests"] == 0 and empty["p50_ms"] is None


class TestLinkage:
    def test_trace_carries_workload_record(self, model, tmp_path):
        cap = WorkloadCapture(tmp_path, num_features=D)
        rec = FlightRecorder(capacity=16, slowest_k=4)
        b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                         index_version="v1", recorder=rec, workload=cap)
        try:
            cap.start()
            handle = b.submit(np.zeros((1, D), np.float32), "predict")
            handle.result(timeout=30)
            rid = handle.meta["request_id"]
            tl = rec.find(rid)
            assert tl is not None
            assert isinstance(tl.get("workload_record"), int)
            assert cap.drain(20)
            summary = cap.stop()
        finally:
            b.close()
            cap.close()
        wl = load_workload(summary["path"])
        ev = wl.read_events[0]
        assert ev["id"] == tl["workload_record"]
        assert ev["request_id"] == rid

    def test_no_capture_no_annotation(self, model):
        rec = FlightRecorder(capacity=16, slowest_k=4)
        b = MicroBatcher(model, max_batch=8, max_wait_ms=0.0,
                         recorder=rec)
        try:
            handle = b.submit(np.zeros((1, D), np.float32), "predict")
            handle.result(timeout=30)
            tl = rec.find(handle.meta["request_id"])
        finally:
            b.close()
        assert "workload_record" not in tl


class TestReplayCLI:
    def test_bad_workload_exits_2(self, tmp_path, capsys):
        from knn_tpu.cli import run

        rc = run(["replay", str(tmp_path / "missing"), "--index",
                  str(tmp_path)])
        assert rc == 2
        assert "error:" in capsys.readouterr().err

    def test_in_process_replay_via_cli(self, model, tmp_path, capsys):
        from knn_tpu.cli import run
        from knn_tpu.serve import artifact

        idx = tmp_path / "idx"
        artifact.save_index(model, idx)
        # Capture against the loaded-artifact version tag so the CLI
        # replay's tag verification engages.
        version = artifact.index_version(artifact.read_manifest(idx))
        summary = _capture_some(model, tmp_path, n=4, version=version)
        rc = run(["replay", summary["path"], "--index", str(idx),
                  "--speed", "0", "--verdict-out",
                  str(tmp_path / "verdict.json"),
                  "--fail-on-divergence"])
        out = capsys.readouterr().out
        assert rc == 0, out
        verdict = json.loads((tmp_path / "verdict.json").read_text())
        assert verdict["verify"]["divergences"] == 0
        assert verdict["verify"]["verified"] == 4
        assert verdict["measured"]["errors"] == 0
        assert "capacity" in verdict


class TestPyArffFallbackWarning:
    def test_large_file_warns_once(self, tmp_path, capsys, monkeypatch):
        from knn_tpu.data import arff as arff_mod

        p = tmp_path / "t.arff"
        p.write_text("@relation t\n@attribute a NUMERIC\n"
                     "@attribute class NUMERIC\n@data\n1,0\n2,1\n")
        # Force the auto path to miss the native lib and cross the
        # (shrunk) size threshold.
        monkeypatch.setattr(arff_mod, "_PY_PARSER_WARN_BYTES", 1)

        def no_native(path):
            raise ImportError("forced off for the test")

        import knn_tpu.native.arff_native as nat

        monkeypatch.setattr(nat, "parse", no_native)
        ds = arff_mod.load_arff(str(p))
        assert ds.num_instances == 2
        err = capsys.readouterr().err
        assert "pure-Python ARFF parser" in err
        assert "make native" in err

    def test_forced_python_stays_silent(self, tmp_path, capsys,
                                        monkeypatch):
        from knn_tpu.data import arff as arff_mod

        p = tmp_path / "t.arff"
        p.write_text("@relation t\n@attribute a NUMERIC\n"
                     "@attribute class NUMERIC\n@data\n1,0\n")
        monkeypatch.setattr(arff_mod, "_PY_PARSER_WARN_BYTES", 1)
        arff_mod.load_arff(str(p), use_native=False)  # explicit choice
        assert "pure-Python ARFF parser" not in capsys.readouterr().err
