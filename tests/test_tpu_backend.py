"""Single-device jit backend: exact prediction parity with the oracle
(predictions, not just accuracy — SURVEY.md §4), full vs tiled equivalence,
golden accuracies."""

import numpy as np
import pytest

from knn_tpu.backends.oracle import knn_oracle
from knn_tpu.backends.tpu import predict_arrays
from knn_tpu.models.knn import KNNClassifier
from tests import fixtures


class TestParityWithOracle:
    @pytest.mark.parametrize("k", [1, 3, 5])
    def test_small_exact_prediction_parity(self, small, k):
        train, test = small
        want = knn_oracle(train.features, train.labels, test.features, k, train.num_classes)
        got = predict_arrays(
            train.features, train.labels, test.features, k, train.num_classes
        )
        np.testing.assert_array_equal(got, want)

    def test_medium_exact_prediction_parity(self, medium):
        train, test = medium
        want = knn_oracle(train.features, train.labels, test.features, 5, train.num_classes)
        got = predict_arrays(
            train.features, train.labels, test.features, 5, train.num_classes
        )
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("k", [1, 5])
    def test_tiled_matches_full(self, medium, k):
        train, test = medium
        full = predict_arrays(
            train.features, train.labels, test.features, k, train.num_classes
        )
        tiled = predict_arrays(
            train.features, train.labels, test.features, k, train.num_classes,
            force_tiled=True, query_tile=128, train_tile=512,
        )
        np.testing.assert_array_equal(tiled, full)

    def test_tiled_ragged_edges(self, rng):
        # Shapes deliberately not multiples of the tile sizes.
        n, q, d, k, c = 1037, 101, 5, 7, 6
        train_x = rng.normal(size=(n, d)).astype(np.float32)
        train_y = rng.integers(0, c, n).astype(np.int32)
        test_x = rng.normal(size=(q, d)).astype(np.float32)
        want = knn_oracle(train_x, train_y, test_x, k, c)
        got = predict_arrays(
            train_x, train_y, test_x, k, c,
            force_tiled=True, query_tile=64, train_tile=256,
        )
        np.testing.assert_array_equal(got, want)

    def test_duplicate_rows_tie_stability(self, rng):
        # Many exact-duplicate train rows across tile boundaries: the winning
        # candidate must be the lowest global train index (SURVEY.md §7 (b)).
        base = rng.integers(0, 3, (64, 4)).astype(np.float32)
        train_x = np.tile(base, (8, 1))  # 512 rows, every row repeated 8x
        train_y = rng.integers(0, 5, 512).astype(np.int32)
        test_x = base[:16]
        want = knn_oracle(train_x, train_y, test_x, 9, 5)
        got = predict_arrays(
            train_x, train_y, test_x, 9, 5,
            force_tiled=True, query_tile=8, train_tile=128,
        )
        np.testing.assert_array_equal(got, want)


class TestNanPolicy:
    def test_nan_features_match_oracle(self):
        # Framework-wide policy: NaN distances count as +inf and inf
        # candidates are admitted in (dist, index) order (SURVEY.md §3.5.5
        # is UB in the reference). All backends must agree.
        train_x = np.array([[1.0], [2.0], [3.0]], np.float32)
        train_y = np.array([2, 2, 1], np.int32)
        test_x = np.array([[np.nan], [2.0]], np.float32)
        want = knn_oracle(train_x, train_y, test_x, 2, 3)
        got = predict_arrays(train_x, train_y, test_x, 2, 3)
        np.testing.assert_array_equal(got, want)
        got_tiled = predict_arrays(
            train_x, train_y, test_x, 2, 3,
            force_tiled=True, query_tile=2, train_tile=2,
        )
        np.testing.assert_array_equal(got_tiled, want)


class TestGolden:
    @pytest.mark.skipif(
        not fixtures.using_reference_datasets(), reason="reference datasets required"
    )
    @pytest.mark.parametrize("size,k", [("small", 1), ("small", 5), ("medium", 5)])
    def test_golden_accuracy(self, size, k, request):
        train, test = request.getfixturevalue(size)
        model = KNNClassifier(k=k, backend="tpu").fit(train)
        assert round(model.score(test), 4) == fixtures.GOLDEN_ACCURACY[(size, k)]

    @pytest.mark.slow
    @pytest.mark.skipif(
        not fixtures.using_reference_datasets(), reason="reference datasets required"
    )
    def test_golden_accuracy_large(self, large):
        train, test = large
        model = KNNClassifier(k=5, backend="tpu", force_tiled=True).fit(train)
        assert round(model.score(test), 4) == 0.9948


class TestApproxTopK:
    def test_approx_mode_runs_and_is_close(self, small):
        # lax.approx_max_k: exact on CPU's fallback path, >=0.95 recall on
        # TPU hardware. Opt-in, documented as not prediction-exact.
        train, test = small
        want = knn_oracle(
            train.features, train.labels, test.features, 5, train.num_classes
        )
        got = predict_arrays(
            train.features, train.labels, test.features, 5, train.num_classes,
            approx=True,
        )
        assert got.shape == want.shape
        assert (got == want).mean() >= 0.9

    def test_cli_flag_plumbs_through(self, small, tmp_path):
        import io

        from knn_tpu.cli import run

        from tests.fixtures import datasets_dir

        d = datasets_dir()
        buf = io.StringIO()
        rc = run(
            [str(d / "small-train.arff"), str(d / "small-test.arff"), "5",
             "--backend", "tpu", "--approx", "--platform", "cpu"],
            stdout=buf,
        )
        assert rc == 0
        assert "The 5-NN classifier for 80 test instances" in buf.getvalue()


class TestQueryBatching:
    """Host-side query streaming: batched must equal unbatched bit-for-bit,
    including a ragged last chunk and both compiled paths."""

    def test_batched_equals_unbatched_full_matrix(self, rng):
        from knn_tpu.backends.tpu import predict_arrays

        train_x = rng.integers(0, 4, (300, 6)).astype(np.float32)
        train_y = rng.integers(0, 7, 300).astype(np.int32)
        test_x = rng.integers(0, 4, (157, 6)).astype(np.float32)  # ragged vs 64
        want = predict_arrays(train_x, train_y, test_x, 3, 7)
        got = predict_arrays(
            train_x, train_y, test_x, 3, 7, query_batch=64
        )
        np.testing.assert_array_equal(got, want)

    def test_batched_equals_unbatched_tiled(self, rng):
        from knn_tpu.backends.tpu import predict_arrays

        train_x = rng.integers(0, 4, (500, 5)).astype(np.float32)
        train_y = rng.integers(0, 5, 500).astype(np.int32)
        test_x = rng.integers(0, 4, (97, 5)).astype(np.float32)
        want = predict_arrays(
            train_x, train_y, test_x, 4, 5, force_tiled=True,
            query_tile=32, train_tile=128,
        )
        got = predict_arrays(
            train_x, train_y, test_x, 4, 5, force_tiled=True,
            query_tile=32, train_tile=128, query_batch=40,
        )
        np.testing.assert_array_equal(got, want)

    def test_cli_flag(self, small_paths):
        import io

        from knn_tpu.cli import run

        train_p, test_p = small_paths
        out = io.StringIO()
        rc = run([train_p, test_p, "1", "--query-batch", "32",
                  "--platform", "cpu"], stdout=out)
        assert rc == 0
        assert "80 test instances" in out.getvalue()


class TestEngineDispatch:
    def test_stripe_engine_matches_oracle_off_tpu(self, rng):
        # engine="stripe" forces the lane-striped Pallas kernel (interpreted
        # on CPU), exercising the same dispatch the TPU auto path takes.
        from knn_tpu.backends.oracle import knn_oracle
        from knn_tpu.backends.tpu import predict_arrays

        train_x = rng.integers(0, 4, (200, 6)).astype(np.float32)
        train_y = rng.integers(0, 5, 200).astype(np.int32)
        test_x = np.concatenate(
            [train_x[:20], rng.integers(0, 4, (21, 6)).astype(np.float32)]
        )
        want = knn_oracle(train_x, train_y, test_x, 3, 5)
        got = predict_arrays(train_x, train_y, test_x, 3, 5, engine="stripe")
        np.testing.assert_array_equal(got, want)

    def test_unknown_engine_rejected(self, rng):
        from knn_tpu.backends.tpu import predict_arrays

        with pytest.raises(ValueError, match="unknown engine"):
            predict_arrays(
                np.zeros((4, 2), np.float32), np.zeros(4, np.int32),
                np.zeros((2, 2), np.float32), 1, 2, engine="Stripe",
            )

    def test_empty_query_set(self):
        from knn_tpu.backends.tpu import predict_arrays

        out = predict_arrays(
            np.zeros((4, 2), np.float32), np.zeros(4, np.int32),
            np.zeros((0, 2), np.float32), 1, 2,
        )
        assert out.shape == (0,) and out.dtype == np.int32


class TestRecallTarget:
    def test_recall_one_matches_exact(self, rng):
        # recall_target=1.0 makes approx_max_k exhaustive: on a problem with
        # distinct distances the predictions must equal the exact path.
        import numpy as np

        from knn_tpu.backends.tpu import predict_arrays

        train_x = rng.normal(size=(300, 6)).astype(np.float32)
        train_y = rng.integers(0, 5, 300).astype(np.int32)
        test_x = rng.normal(size=(40, 6)).astype(np.float32)
        want = predict_arrays(train_x, train_y, test_x, 5, 5)
        got = predict_arrays(
            train_x, train_y, test_x, 5, 5, approx=True, recall_target=1.0
        )
        np.testing.assert_array_equal(got, want)

    def test_bad_recall_rejected(self, small):
        import pytest

        from knn_tpu.backends import get_backend

        train, test = small
        with pytest.raises(ValueError, match="recall_target"):
            get_backend("tpu")(train, test, 3, approx=True, recall_target=1.5)
