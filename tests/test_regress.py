"""Perf-regression gate (knn_tpu/obs/regress.py + scripts/bench_gate.py):
the best-of-mins + MAD-tolerance rule — clean pass, injected regression
fails, noise within the MAD tolerance passes (ISSUE 6 acceptance).
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

import pytest

from knn_tpu.obs import regress

REPO = Path(__file__).resolve().parent.parent


def record(metrics: dict) -> dict:
    return {
        "env": {"platform": "cpu", "device_kind": "cpu", "cpus": 2},
        "metrics": {
            name: {"trials": trials, "direction": direction, "unit": "ms"}
            for name, (trials, direction) in metrics.items()
        },
    }


class TestMad:
    def test_median_and_mad(self):
        assert regress.median([3.0, 1.0, 2.0]) == 2.0
        assert regress.median([4.0, 1.0, 2.0, 3.0]) == 2.5
        assert regress.mad([10.0, 12.0, 11.0, 50.0]) == 1.0  # robust to 50
        assert regress.mad([7.0]) == 0.0


class TestCompareMetric:
    BASE = [10.0, 10.5, 11.0, 10.2, 10.8]  # best 10, MAD 0.3

    def test_clean_pass(self):
        c = regress.compare_metric("m", self.BASE, [10.1, 10.4, 10.9])
        assert not c["regressed"] and not c["improved"]

    def test_injected_regression_fails(self):
        c = regress.compare_metric("m", self.BASE,
                                   [t * 2 for t in self.BASE])
        assert c["regressed"]
        assert c["delta"] == pytest.approx(10.0)

    def test_noise_within_mad_tolerance_passes(self):
        # tolerance = max(5% * 10, 5 * MAD 0.3, 0.5 floor) = 1.5 ms;
        # +1.2 ms of noise on the best must NOT gate...
        c = regress.compare_metric("m", self.BASE, [11.2, 11.4, 11.3])
        assert c["tolerance"] == pytest.approx(1.5)
        assert not c["regressed"]
        # ...and just past it does.
        c2 = regress.compare_metric("m", self.BASE, [11.6, 12.0])
        assert c2["regressed"]

    def test_higher_is_better_direction(self):
        base = [100.0, 98.0, 99.0]  # qps-style
        ok = regress.compare_metric("q", base, [97.0, 96.5],
                                    direction="higher")
        assert not ok["regressed"]  # within 5% of 100
        bad = regress.compare_metric("q", base, [50.0, 49.0],
                                     direction="higher")
        assert bad["regressed"]

    def test_improvement_reported_not_failed(self):
        c = regress.compare_metric("m", self.BASE, [5.0, 5.2])
        assert c["improved"] and not c["regressed"]

    def test_abs_floor_shields_microsecond_metrics(self):
        c = regress.compare_metric("m", [0.01, 0.012], [0.3, 0.31])
        assert not c["regressed"]  # 0.29 ms delta < 0.5 ms floor

    def test_empty_trials_fail(self):
        assert regress.compare_metric("m", [], [1.0])["regressed"]
        assert regress.compare_metric("m", [1.0], [])["regressed"]

    def test_bad_direction_raises(self):
        with pytest.raises(ValueError):
            regress.compare_metric("m", [1.0], [1.0], direction="sideways")


class TestCompareRecords:
    def test_verdict_shape_and_pass(self):
        base = record({"a": ([10.0, 10.1], "lower"),
                       "b": ([5.0, 5.1], "lower")})
        v = regress.compare_records(base, base)
        assert v["pass"] is True
        assert len(v["checks"]) == 2
        assert v["new_metrics"] == []

    def test_missing_metric_fails(self):
        base = record({"a": ([10.0], "lower"), "b": ([5.0], "lower")})
        fresh = record({"a": ([10.0], "lower")})
        v = regress.compare_records(base, fresh)
        assert v["pass"] is False
        missing = [c for c in v["checks"] if "reason" in c]
        assert missing and missing[0]["metric"] == "b"

    def test_new_metric_reported_not_gated(self):
        base = record({"a": ([10.0], "lower")})
        fresh = record({"a": ([10.0], "lower"), "c": ([1.0], "lower")})
        v = regress.compare_records(base, fresh)
        assert v["pass"] is True
        assert v["new_metrics"] == ["c"]

    def test_summarize_names_the_regression(self):
        base = record({"a": ([10.0, 10.1], "lower")})
        fresh = record({"a": ([30.0, 30.5], "lower")})
        v = regress.compare_records(base, fresh)
        assert "REGRESSED a:" in regress.summarize(v)


class TestBenchGateScript:
    """scripts/bench_gate.py exit-code contract, driven with --fresh
    records so no measurement runs."""

    @pytest.fixture()
    def gate(self):
        sys.path.insert(0, str(REPO / "scripts"))
        import bench_gate

        return bench_gate

    def _write(self, path: Path, doc: dict) -> str:
        path.write_text(json.dumps(doc))
        return str(path)

    def test_clean_run_passes(self, gate, tmp_path):
        rec = record({"a": ([10.0, 10.3, 10.1], "lower")})
        baseline = self._write(tmp_path / "base.json",
                               {"entries": {gate.env_key(rec): rec}})
        out = tmp_path / "verdict.json"
        rc = gate.main(["--fresh", self._write(tmp_path / "fresh.json", rec),
                        "--baseline", baseline, "--out", str(out)])
        assert rc == 0
        verdict = json.loads(out.read_text())
        assert verdict["pass"] is True and verdict["status"] == "compared"

    def test_synthetically_slowed_record_fails(self, gate, tmp_path):
        rec = record({"a": ([10.0, 10.3, 10.1], "lower")})
        slow = record({"a": ([20.0, 20.6, 20.2], "lower")})
        baseline = self._write(tmp_path / "base.json",
                               {"entries": {gate.env_key(rec): rec}})
        out = tmp_path / "verdict.json"
        rc = gate.main(["--fresh", self._write(tmp_path / "slow.json", slow),
                        "--baseline", baseline, "--out", str(out)])
        assert rc == 1
        verdict = json.loads(out.read_text())
        assert verdict["pass"] is False
        assert verdict["checks"][0]["regressed"]

    def test_unknown_env_passes_unarmed(self, gate, tmp_path):
        rec = record({"a": ([10.0], "lower")})
        rec["env"]["device_kind"] = "never-seen-device"
        baseline = self._write(tmp_path / "base.json", {"entries": {}})
        out = tmp_path / "verdict.json"
        rc = gate.main(["--fresh", self._write(tmp_path / "f.json", rec),
                        "--baseline", baseline, "--out", str(out)])
        assert rc == 0
        assert json.loads(out.read_text())["status"] == "no-baseline"

    def test_write_baseline_then_compare(self, gate, tmp_path):
        rec = record({"a": ([10.0, 10.2], "lower")})
        baseline = tmp_path / "base.json"
        out = tmp_path / "verdict.json"
        fresh = self._write(tmp_path / "f.json", rec)
        rc = gate.main(["--fresh", fresh, "--baseline", str(baseline),
                        "--out", str(out), "--write-baseline"])
        assert rc == 0
        assert gate.env_key(rec) in json.loads(
            baseline.read_text())["entries"]
        rc = gate.main(["--fresh", fresh, "--baseline", str(baseline),
                        "--out", str(out)])
        assert rc == 0

    def test_unreadable_fresh_exits_2(self, gate, tmp_path):
        rc = gate.main(["--fresh", str(tmp_path / "absent.json"),
                        "--out", str(tmp_path / "v.json")])
        assert rc == 2

    def test_committed_baseline_is_valid(self, gate):
        """The repo's committed baseline parses and carries trial lists."""
        doc = json.loads((REPO / "BENCH_GATE_BASELINE.json").read_text())
        assert doc["entries"]
        for rec in doc["entries"].values():
            for m in rec["metrics"].values():
                assert m["trials"], "baseline metric with no trials"


class TestCommAuditParseError:
    """ISSUE 6 satellite: an empty collective parse raises the DISTINCT
    format-changed error, and the unquoted StableHLO spelling parses."""

    def test_unquoted_spelling_parses(self):
        from knn_tpu.parallel import comm_audit

        text = ('%3 = stablehlo.all_gather(%2) {dims = [1]} : '
                '(tensor<4x15xf32>) -> tensor<4x30xf32>')
        ops = comm_audit.collective_ops(text)
        assert ops == [("all_gather", (4, 30), "f32", 4 * 30 * 4)]

    def test_empty_parse_raises_distinct_error(self):
        from knn_tpu.parallel import comm_audit

        with pytest.raises(comm_audit.CollectiveParseError,
                           match="lowering format changed"):
            comm_audit.audit_train_sharded("no collectives here", 4, 3, 2)
        with pytest.raises(comm_audit.CollectiveParseError,
                           match="lowering format changed"):
            comm_audit.audit_ring("nothing", 100, 10, 2)
