"""Parallel native @data scan (VERDICT r4 #5).

The two-pass segmented scanner must COMMIT only results that are
bit-identical to the serial scanner's, and fall back to serial for
everything else (quotes, STRING/DATE interning, any error). These tests
drive both paths explicitly via KNN_ARFF_THREADS — the CI box has one
core, so the default path is serial there and the parallel machinery
would otherwise go untested.

Files are built >= the 4 MB engagement threshold by replicating a body;
every comparison is full-array bitwise equality.
"""

import os

import numpy as np
import pytest

from knn_tpu.native import arff_native


def _write_big(tmp_path, name, header, body_lines, reps):
    body = "\n".join(body_lines) + "\n"
    target = 5 * 1024 * 1024
    body_reps = max(reps, target // max(len(body), 1) + 1)
    p = tmp_path / name
    with open(p, "w") as f:
        f.write(header)
        for _ in range(body_reps):
            f.write(body)
    assert os.path.getsize(p) >= 4 << 20
    return str(p)


def _parse_with_threads(path, threads):
    old = os.environ.get("KNN_ARFF_THREADS")
    os.environ["KNN_ARFF_THREADS"] = str(threads)
    try:
        return arff_native.parse(path)
    finally:
        if old is None:
            del os.environ["KNN_ARFF_THREADS"]
        else:
            os.environ["KNN_ARFF_THREADS"] = old


def _assert_equal(path):
    serial = _parse_with_threads(path, 1)
    par = _parse_with_threads(path, 4)
    assert par.num_instances == serial.num_instances
    np.testing.assert_array_equal(par.features, serial.features)
    np.testing.assert_array_equal(par.labels, serial.labels)
    np.testing.assert_array_equal(par.raw_targets, serial.raw_targets)
    return serial


HEADER = (
    "@relation big\n"
    "@attribute a NUMERIC\n@attribute b NUMERIC\n"
    "@attribute c NUMERIC\n@attribute class NUMERIC\n@data\n"
)


class TestParallelMatchesSerial:
    def test_plain_numeric(self, tmp_path):
        lines = [f"{i}.25,{i * 3}.5,-{i}.125,{i % 7}" for i in range(50)]
        ds = _assert_equal(_write_big(tmp_path, "plain.arff", HEADER, lines, 1))
        assert ds.num_instances > 100000

    def test_comments_blanks_and_missing(self, tmp_path):
        lines = [
            "1.5,2.5,?,0",
            "% a comment line with, commas and 9.9 digits",
            "",
            "   ",
            "3.25,?,4.5,1",
        ]
        ds = _assert_equal(
            _write_big(tmp_path, "comments.arff", HEADER, lines, 1))
        assert np.isnan(ds.features).any()

    def test_rows_spanning_lines_and_partial_eof(self, tmp_path):
        # Rows deliberately span physical lines (2 cells per line, 4 per
        # row), and the file ends mid-row: the partial row is discarded by
        # both paths.
        lines = [f"{i}.5,{i}.75" for i in range(40)]
        path = _write_big(tmp_path, "span.arff", HEADER, lines, 1)
        with open(path, "a") as f:
            f.write("7.5,8.5,9.5")  # 3 of 4 cells -> discarded
        _assert_equal(path)

    def test_nominal_attributes(self, tmp_path):
        header = (
            "@relation big\n"
            "@attribute a NUMERIC\n"
            "@attribute color {red, green, blue}\n"
            "@attribute class NUMERIC\n@data\n"
        )
        lines = [f"{i}.5,{c},{i % 3}" for i, c in zip(
            range(60), ["red", "green", "blue"] * 20)]
        ds = _assert_equal(
            _write_big(tmp_path, "nominal.arff", header, lines, 1))
        assert set(np.unique(ds.features[:, 1])) == {0.0, 1.0, 2.0}

    def test_trailing_comma_and_crlf(self, tmp_path):
        lines = ["1.5,2.5,3.5,0,\r", "4.5,5.5,6.5,1,\r"]
        _assert_equal(_write_big(tmp_path, "crlf.arff", HEADER, lines, 1))

    def test_quoted_cells_fall_back_to_serial(self, tmp_path):
        # Quotes are outside the parallel subset; the fallback must still
        # produce the serial result (and the quoted cells must parse).
        lines = ["'1.5',2.5,3.5,0", "4.5,'5.5',6.5,1"]
        ds = _assert_equal(
            _write_big(tmp_path, "quoted.arff", HEADER, lines, 1))
        assert ds.features[0, 0] == 1.5

    def test_malformed_value_reports_serial_diagnostic(self, tmp_path):
        lines = [f"{i}.5,1.5,2.5,0" for i in range(50)]
        path = _write_big(tmp_path, "bad.arff", HEADER, lines, 1)
        with open(path, "a") as f:
            f.write("1.5,oops,2.5,0\n3.5,4.5,5.5,1\n")
        with pytest.raises(ValueError) as e_ser:
            _parse_with_threads(path, 1)
        with pytest.raises(ValueError) as e_par:
            _parse_with_threads(path, 4)
        # Byte-identical message: the parallel path reruns serially on any
        # error, so the diagnostic (message, file:line) is the serial one.
        assert str(e_ser.value) == str(e_par.value)
        assert "oops" in str(e_par.value)

    def test_empty_cell_reports_serial_diagnostic(self, tmp_path):
        lines = [f"{i}.5,1.5,2.5,0" for i in range(50)]
        path = _write_big(tmp_path, "empty.arff", HEADER, lines, 1)
        with open(path, "a") as f:
            f.write("1.5,,2.5,0\n")
        with pytest.raises(ValueError) as e_ser:
            _parse_with_threads(path, 1)
        with pytest.raises(ValueError) as e_par:
            _parse_with_threads(path, 4)
        assert str(e_ser.value) == str(e_par.value)

    def test_string_attrs_use_serial_interning(self, tmp_path):
        header = (
            "@relation big\n"
            "@attribute a NUMERIC\n"
            "@attribute s STRING\n"
            "@attribute class NUMERIC\n@data\n"
        )
        lines = [f"{i}.5,w{i % 5},{i % 3}" for i in range(60)]
        ds = _assert_equal(
            _write_big(tmp_path, "strings.arff", header, lines, 1))
        # First-seen intern order: w0..w4 -> codes 0..4.
        assert ds.features[0, 1] == 0.0 and ds.features[4, 1] == 4.0
