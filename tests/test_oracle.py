"""Oracle backend: golden accuracies + kernel-contract property tests
(SURVEY.md §3.5)."""

import numpy as np
import pytest

from knn_tpu.backends.oracle import knn_oracle, predict as oracle_predict
from knn_tpu.models.knn import KNNClassifier
from tests import fixtures


needs_reference = pytest.mark.skipif(
    not fixtures.using_reference_datasets(),
    reason="golden accuracies only valid for the reference datasets",
)


class TestGoldenAccuracy:
    """Measured from the reference binaries; serial ≡ pthread (BASELINE.md)."""

    @needs_reference
    @pytest.mark.parametrize(
        "size,k",
        [("small", 1), ("small", 5), ("medium", 5), ("large", 1), ("large", 5), ("large", 10)],
    )
    def test_golden(self, size, k, request):
        train, test = request.getfixturevalue(size)
        model = KNNClassifier(k=k, backend="oracle").fit(train)
        acc = model.score(test)
        assert round(acc, 4) == fixtures.GOLDEN_ACCURACY[(size, k)]


class TestKernelContract:
    def test_distance_excludes_class_column(self):
        # Class is the last attribute and never enters the distance (main.cpp:17).
        train_x = np.array([[0.0, 0.0], [10.0, 10.0]], np.float32)
        train_y = np.array([7, 3], np.int32)
        test_x = np.array([[0.1, 0.1]], np.float32)
        assert knn_oracle(train_x, train_y, test_x, 1, 10)[0] == 7

    def test_distance_tie_first_train_index_wins(self):
        # Equal distances: earliest-scanned train index wins (main.cpp:46-61).
        train_x = np.array([[1.0], [1.0], [1.0]], np.float32)
        train_y = np.array([5, 2, 9], np.int32)
        test_x = np.array([[1.0]], np.float32)
        assert knn_oracle(train_x, train_y, test_x, 1, 10)[0] == 5
        # k=2 keeps indices 0 and 1 -> vote tie 5 vs 2 -> lowest class id (2).
        assert knn_oracle(train_x, train_y, test_x, 2, 10)[0] == 2

    def test_vote_tie_lowest_class_wins(self):
        # Strict > argmax from -1 (main.cpp:69-76).
        train_x = np.array([[0.0], [1.0], [2.0], [3.0]], np.float32)
        train_y = np.array([8, 1, 8, 1], np.int32)
        test_x = np.array([[1.5]], np.float32)
        # k=4: two votes each for 1 and 8 -> predict 1.
        assert knn_oracle(train_x, train_y, test_x, 4, 10)[0] == 1

    def test_k_equals_n(self):
        train_x = np.arange(6, dtype=np.float32).reshape(3, 2)
        train_y = np.array([0, 1, 1], np.int32)
        test_x = np.array([[0.0, 0.0]], np.float32)
        assert knn_oracle(train_x, train_y, test_x, 3, 2)[0] == 1

    def test_k_greater_than_n_rejected(self, small):
        # The reference makes this UB (SURVEY.md §3.5.5); we validate.
        train, test = small
        with pytest.raises(ValueError, match="exceeds"):
            oracle_predict(train, test, train.num_instances + 1)

    def test_k_zero_rejected(self, small):
        train, test = small
        with pytest.raises(ValueError, match="k must be"):
            KNNClassifier(k=0)

    def test_feature_dim_mismatch_rejected(self, small, medium):
        with pytest.raises(ValueError, match="features"):
            oracle_predict(small[0], medium[1], 1)

    def test_against_bruteforce(self, rng):
        """Property test vs a literal transcription of the insertion-sort kernel."""
        for _ in range(10):
            n, q, d, k, c = 40, 12, 3, 5, 4
            train_x = rng.integers(0, 4, (n, d)).astype(np.float32)  # many ties
            train_y = rng.integers(0, c, n).astype(np.int32)
            test_x = rng.integers(0, 4, (q, d)).astype(np.float32)
            got = knn_oracle(train_x, train_y, test_x, k, c)
            want = _bruteforce(train_x, train_y, test_x, k, c)
            np.testing.assert_array_equal(got, want)


def _bruteforce(train_x, train_y, test_x, k, num_classes):
    """Direct transcription of the reference candidate-insertion loop
    (main.cpp:40-82) in Python, as an independent contract witness."""
    out = []
    for qx in test_x:
        cand = [(np.float32(np.finfo(np.float32).max), -1)] * k
        for i, tx in enumerate(train_x):
            dist = np.float32(0)
            for a, b in zip(qx, tx):
                dist += np.float32((a - b) * (a - b))
            for c in range(k):
                if dist < cand[c][0]:  # strict < : first-seen wins ties
                    cand = cand[:c] + [(dist, int(train_y[i]))] + cand[c:-1]
                    break
        counts = [0] * num_classes
        for _, lbl in cand:
            if lbl >= 0:
                counts[lbl] += 1
        best, best_c = -1, 0
        for ci, cnt in enumerate(counts):
            if cnt > best:  # strict > : lowest class wins ties
                best, best_c = cnt, ci
        out.append(best_c)
    return np.array(out, np.int32)


class TestModelAPI:
    """kneighbors / predict_proba — retrieval surface beyond the reference."""

    def test_kneighbors_matches_oracle_order(self, rng):
        from knn_tpu.data.dataset import Dataset
        from knn_tpu.models.knn import KNNClassifier

        base = rng.integers(0, 3, (40, 4)).astype(np.float32)
        train_x = np.tile(base, (4, 1))  # duplicates -> dist==0 ties
        train_y = rng.integers(0, 5, 160).astype(np.int32)
        test_x = base[:12]
        train = Dataset(features=train_x, labels=train_y)
        test = Dataset(features=test_x, labels=np.zeros(12, np.int32))
        k = 6
        model = KNNClassifier(k=k, backend="tpu").fit(train)
        d, i = model.kneighbors(test)
        assert d.shape == (12, k) and i.shape == (12, k)
        # Reference tie-break order: stable lexicographic (distance, index).
        diff = test_x[:, None, :] - train_x[None, :, :]
        dists = np.einsum("qnd,qnd->qn", diff, diff, dtype=np.float32)
        for row in range(12):
            want = np.lexsort((np.arange(160), dists[row]))[:k]
            np.testing.assert_array_equal(i[row], want)

    def test_predict_proba_consistent_with_predict(self, small):
        from knn_tpu.models.knn import KNNClassifier

        train, test = small
        model = KNNClassifier(k=5, backend="tpu").fit(train)
        proba = model.predict_proba(test)
        assert proba.shape == (test.num_instances, train.num_classes)
        np.testing.assert_allclose(proba.sum(axis=1), 1.0)
        np.testing.assert_array_equal(proba.argmax(axis=1), model.predict(test))
