"""Cross-backend prediction equality — the framework-wide catch-all.

The reference's de-facto verification is accuracy equality across its three
binaries (SURVEY.md §4); this is the stronger form: every registered backend
must produce *identical predictions* (not just accuracy) on a tie-heavy
problem, so a new backend cannot silently diverge on the §3.5 contract.
"""

import numpy as np
import pytest

from knn_tpu.backends import available_backends, get_backend
from knn_tpu.backends.oracle import knn_oracle
from knn_tpu.data.dataset import Dataset


@pytest.fixture(scope="module")
def tie_problem():
    rng = np.random.default_rng(7)
    base = rng.integers(0, 4, (50, 6)).astype(np.float32)
    train_x = np.tile(base, (5, 1))  # every row 5x -> dist==0 ties everywhere
    train_y = rng.integers(0, 7, 250).astype(np.int32)
    test_x = np.concatenate(
        [base[:20], rng.integers(0, 4, (13, 6)).astype(np.float32)]
    )
    train = Dataset(features=train_x, labels=train_y)
    test = Dataset(features=test_x, labels=np.zeros(33, np.int32))
    want = knn_oracle(train_x, train_y, test_x, 5, train.num_classes)
    return train, test, want


def test_all_backends_registered():
    names = available_backends()
    for expected in (
        "oracle", "tpu", "tpu-sharded", "tpu-train-sharded", "tpu-ring",
        "tpu-pallas", "native", "native-mt",
    ):
        assert expected in names, f"backend '{expected}' missing from registry"


@pytest.mark.parametrize("name", [
    "oracle", "tpu", "tpu-sharded", "tpu-train-sharded", "tpu-ring",
    "tpu-pallas", "native", "native-mt",
])
def test_backend_prediction_equality(tie_problem, name):
    if name not in available_backends():
        pytest.skip(f"{name} unavailable in this environment")
    train, test, want = tie_problem
    got = get_backend(name)(train, test, 5)
    np.testing.assert_array_equal(got, want)
