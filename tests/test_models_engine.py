"""Model retrieval honors engine selection (VERDICT r1 #6): ``kneighbors`` /
``predict_proba`` / weighted vote / regression route through the same engine
knob as ``predict``, and every engine returns identical (distance, index)
candidates on tie-dense problems."""

import numpy as np
import pytest

from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier, KNNRegressor, _kneighbors_arrays


def _tie_problem(rng, n=400, q=50, d=5, c=6):
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)  # grid → ties
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, q // 2, replace=False)],
         rng.integers(0, 4, (q - q // 2, d)).astype(np.float32)]
    )
    return train_x, train_y, test_x, c


class TestKneighborsEngines:
    @pytest.mark.parametrize("k", [1, 5, 12])
    def test_stripe_matches_xla(self, rng, k):
        train_x, train_y, test_x, _ = _tie_problem(rng)
        d_x, i_x = _kneighbors_arrays(train_x, test_x, k, engine="xla")
        d_s, i_s = _kneighbors_arrays(train_x, test_x, k, engine="stripe")
        np.testing.assert_array_equal(i_s, i_x)
        np.testing.assert_array_equal(d_s, d_x)

    def test_candidates_match_brute_force(self, rng):
        train_x, _, test_x, _ = _tie_problem(rng, n=120, q=16)
        k = 7
        for engine in ("xla", "stripe"):
            d, i = _kneighbors_arrays(train_x, test_x, k, engine=engine)
            for row in range(test_x.shape[0]):
                full = ((test_x[row][None, :] - train_x) ** 2).sum(-1)
                order = np.lexsort((np.arange(len(full)), full))[:k]
                np.testing.assert_array_equal(i[row], order, err_msg=engine)

    def test_unknown_engine_rejected(self, rng):
        train_x, _, test_x, _ = _tie_problem(rng, n=32, q=4)
        with pytest.raises(ValueError, match="engine"):
            _kneighbors_arrays(train_x, test_x, 3, engine="warp")

    def test_stripe_rejects_non_euclidean(self, rng):
        train_x, _, test_x, _ = _tie_problem(rng, n=32, q=4)
        with pytest.raises(ValueError, match="euclidean"):
            _kneighbors_arrays(
                train_x, test_x, 3, metric="manhattan", engine="stripe"
            )


class TestModelEngineRouting:
    def test_classifier_kneighbors_engine_opt(self, rng):
        train_x, train_y, test_x, c = _tie_problem(rng)
        train = Dataset(train_x, train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        m_x = KNNClassifier(k=5, engine="xla").fit(train)
        m_s = KNNClassifier(k=5, engine="stripe").fit(train)
        d_x, i_x = m_x.kneighbors(test)
        d_s, i_s = m_s.kneighbors(test)
        np.testing.assert_array_equal(i_s, i_x)
        np.testing.assert_array_equal(d_s, d_x)

    def test_ring_engine_opt_does_not_break_retrieval(self, rng):
        # engine='tiled'/'full' are ring-only per-step scorers; model
        # retrieval must translate them to auto, not crash.
        train_x, train_y, test_x, c = _tie_problem(rng)
        train = Dataset(train_x, train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        m = KNNClassifier(k=5, backend="tpu-ring", engine="tiled").fit(train)
        want = KNNClassifier(k=5).fit(train)
        np.testing.assert_array_equal(
            m.kneighbors(test)[1], want.kneighbors(test)[1]
        )
        assert m.predict_proba(test).shape == (len(test_x), train.num_classes)

    def test_weighted_vote_accepts_engine(self, rng):
        train_x, train_y, test_x, c = _tie_problem(rng)
        train = Dataset(train_x, train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        want = KNNClassifier(k=5, weights="distance").fit(train).predict(test)
        got = (
            KNNClassifier(k=5, weights="distance", engine="stripe")
            .fit(train).predict(test)
        )
        np.testing.assert_array_equal(got, want)

    def test_weighted_vote_still_rejects_other_opts(self):
        with pytest.raises(ValueError, match="engine"):
            KNNClassifier(k=5, weights="distance", query_tile=64)

    def test_regressor_engine_parity(self, rng):
        train_x, _, test_x, _ = _tie_problem(rng)
        targets = rng.normal(size=len(train_x)).astype(np.float32)
        train = Dataset(
            train_x, np.zeros(len(train_x), np.int32), raw_targets=targets
        )
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        p_x = KNNRegressor(k=5, weights="distance", engine="xla").fit(train).predict(test)
        p_s = KNNRegressor(k=5, weights="distance", engine="stripe").fit(train).predict(test)
        np.testing.assert_array_equal(p_s, p_x)


class TestDeviceCache:
    """Dataset.device_cache: repeat retrieval/predict calls reuse the
    device-side train layout instead of re-padding/re-uploading."""

    @pytest.mark.parametrize("engine", ["stripe", "xla"])
    def test_kneighbors_populates_and_reuses_cache(self, rng, engine):
        train_x, train_y, test_x, c = _tie_problem(rng)
        train = Dataset(train_x, train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        m = KNNClassifier(k=5, engine=engine).fit(train)
        d1, i1 = m.kneighbors(test)
        assert train.device_cache, "first call must populate the cache"
        snapshot = {k: v for k, v in train.device_cache.items()}
        d2, i2 = m.kneighbors(test)
        for k_ in snapshot:
            assert train.device_cache[k_] is snapshot[k_], \
                "second call must reuse the cached device arrays"
        np.testing.assert_array_equal(i1, i2)
        np.testing.assert_array_equal(d1, d2)

    def test_backend_predict_uses_dataset_cache(self, rng):
        from knn_tpu.backends import get_backend

        train_x, train_y, test_x, c = _tie_problem(rng)
        train = Dataset(train_x, train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        fn = get_backend("tpu")
        p1 = fn(train, test, 5, engine="stripe")
        assert train.device_cache
        p2 = fn(train, test, 5, engine="stripe")
        np.testing.assert_array_equal(p1, p2)

    def test_inplace_mutation_raises(self, rng):
        # The ENFORCED contract (VERDICT r3 #8): the array attributes are
        # read-only — in-place writes that would silently serve a stale
        # device cache raise instead of corrupting results.
        train_x, train_y, test_x, c = _tie_problem(rng)
        train = Dataset(train_x.copy(), train_y)
        with pytest.raises(ValueError, match="read-only"):
            train.features[:] = np.flipud(train.features.copy())
        with pytest.raises(ValueError, match="read-only"):
            train.labels[0] = 1

    def test_pickle_round_trip_drops_cache_and_stays_frozen(self, rng):
        # Model persistence contract: pickling a fitted model carries the
        # data but NOT the device cache (padded/transposed duplicates that
        # would re-home on whatever backend loads them); unpickled arrays
        # stay read-only so the staleness contract survives the trip; and
        # predictions are identical after reload.
        import pickle

        train_x, train_y, test_x, c = _tie_problem(rng)
        train = Dataset(train_x.copy(), train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        m = KNNClassifier(k=3, engine="stripe").fit(train)
        _, idx1 = m.kneighbors(test)
        assert m.train_.device_cache  # populated by the retrieval
        m2 = pickle.loads(pickle.dumps(m))
        assert m2.train_.device_cache == {}
        with pytest.raises(ValueError, match="read-only"):
            m2.train_.features[:] = 0
        _, idx2 = m2.kneighbors(test)
        np.testing.assert_array_equal(idx1, idx2)

    def test_dataclasses_replace_gets_fresh_cache(self, rng):
        # dataclasses.replace passes the ORIGINAL instance's device_cache
        # dict to the new instance; its layouts describe the old arrays, so
        # the new instance must start with a fresh cache.
        import dataclasses

        train_x, train_y, test_x, c = _tie_problem(rng)
        train = Dataset(train_x.copy(), train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        m = KNNClassifier(k=3, engine="stripe").fit(train)
        m.kneighbors(test)  # populate
        assert train.device_cache
        flipped = np.flipud(np.asarray(train.features).copy())
        train2 = dataclasses.replace(train, features=flipped)
        assert train2.device_cache == {}
        assert train2.device_cache is not train.device_cache
        _, idx = KNNClassifier(k=3, engine="stripe").fit(train2).kneighbors(test)
        fresh = Dataset(flipped.copy(), train_y)
        want = KNNClassifier(k=3, engine="stripe").fit(fresh).kneighbors(test)[1]
        np.testing.assert_array_equal(idx, want)

    def test_rebinding_arrays_clears_device_cache(self, rng):
        # Rebinding an array attribute invalidates cached device layouts
        # automatically; subsequent retrievals reflect the new data.
        train_x, train_y, test_x, c = _tie_problem(rng)
        train = Dataset(train_x.copy(), train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        m = KNNClassifier(k=3, engine="stripe").fit(train)
        m.kneighbors(test)  # populate
        assert train.device_cache
        train.features = np.flipud(np.asarray(train.features).copy())
        assert not train.device_cache  # auto-cleared, no clear() call needed
        _, idx = m.kneighbors(test)
        fresh = Dataset(np.asarray(train.features).copy(), train_y)
        want = KNNClassifier(k=3, engine="stripe").fit(fresh).kneighbors(test)[1]
        np.testing.assert_array_equal(idx, want)


class TestSweepK:
    """sweep_k: every k's predictions from one shared retrieval must equal an
    individual predict at that k (prefix-vote exactness under the
    (distance, index) tie contract)."""

    @pytest.mark.parametrize("engine", ["stripe", "xla"])
    def test_matches_individual_predicts(self, rng, engine):
        from knn_tpu.models.knn import sweep_k

        train_x, train_y, test_x, c = _tie_problem(rng)
        train = Dataset(train_x, train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        ks = [1, 3, 7, 12]
        got = sweep_k(train, test, ks, engine=engine)
        assert sorted(got) == ks
        for k in ks:
            want = KNNClassifier(k=k, engine=engine).fit(train).predict(test)
            np.testing.assert_array_equal(got[k], want)

    def test_duplicate_and_unsorted_ks(self, rng):
        from knn_tpu.models.knn import sweep_k

        train_x, train_y, test_x, c = _tie_problem(rng, n=64, q=8)
        train = Dataset(train_x, train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        got = sweep_k(train, test, [5, 1, 5])
        assert sorted(got) == [1, 5]

    def test_rejects_bad_ks(self, rng):
        from knn_tpu.models.knn import sweep_k

        train_x, train_y, test_x, c = _tie_problem(rng, n=64, q=8)
        train = Dataset(train_x, train_y)
        test = Dataset(test_x, np.zeros(len(test_x), np.int32))
        with pytest.raises(ValueError):
            sweep_k(train, test, [])
        with pytest.raises(ValueError):
            sweep_k(train, test, [0, 5])
        with pytest.raises(ValueError):
            sweep_k(train, test, [len(train_x) + 1])  # validate_for_knn
