"""Fleet aggregation (knn_tpu/obs/aggregate.py): registry snapshots,
proc-labeled merges, straggler math — pinned with fake registries where
jaxlib lacks multi-process collectives (the ISSUE 6 acceptance contract)
— plus the per-strategy knn_shard_dispatch_ms gauges the straggler
signal is built from.
"""

from __future__ import annotations

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.obs import aggregate
from knn_tpu.obs.metrics import MetricsRegistry


@pytest.fixture()
def global_obs():
    obs.reset()
    obs.enable()
    yield obs
    obs.disable()
    obs.reset()


def make_proc_registry(proc: int, dispatch_ms: dict) -> MetricsRegistry:
    """A fake per-process registry with the instrument mix the real
    strategies record."""
    reg = MetricsRegistry()
    reg.counter("knn_predict_calls_total", backend="tpu").add(10 + proc)
    reg.gauge("knn_predict_qps", backend="tpu").set(100.0 * (proc + 1))
    h = reg.histogram("knn_predict_wall_ms", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 5.0, 50.0 + proc):
        h.observe(v)
    for path, ms in dispatch_ms.items():
        reg.gauge("knn_shard_dispatch_ms", path=path).set(ms)
    return reg


class TestSnapshot:
    def test_round_trips_all_kinds(self):
        reg = make_proc_registry(0, {"ring": 12.5})
        snap = aggregate.snapshot_registry(reg)
        by_name = {r["name"]: r for r in snap}
        assert by_name["knn_predict_calls_total"]["value"] == 10
        assert by_name["knn_predict_qps"]["value"] == 100.0
        h = by_name["knn_predict_wall_ms"]
        assert h["buckets"] == [1.0, 10.0, 100.0]
        assert h["counts"] == [1, 1, 1, 0]  # raw, incl. +Inf overflow
        assert h["count"] == 3
        assert by_name["knn_shard_dispatch_ms"]["labels"] == {"path": "ring"}

    def test_json_round_trip(self):
        import json

        snap = aggregate.snapshot_registry(make_proc_registry(1, {}))
        assert json.loads(json.dumps(snap)) == snap


class TestMerge:
    def test_merge_adds_proc_labels_and_preserves_values(self):
        snaps = {
            p: aggregate.snapshot_registry(make_proc_registry(p, {}))
            for p in (0, 1, 2)
        }
        merged = aggregate.merge_snapshots(snaps)
        counters = {
            dict(i.labels)["proc"]: i.value
            for i in merged.instruments()
            if i.name == "knn_predict_calls_total"
        }
        assert counters == {"0": 10, "1": 11, "2": 12}
        # Per-proc attribution survives: nothing was summed across procs.
        gauges = {
            dict(i.labels)["proc"]: i.value
            for i in merged.instruments()
            if i.name == "knn_predict_qps"
        }
        assert gauges == {"0": 100.0, "1": 200.0, "2": 300.0}

    def test_histogram_merge_exact(self):
        snaps = {
            p: aggregate.snapshot_registry(make_proc_registry(p, {}))
            for p in (0, 1)
        }
        merged = aggregate.merge_snapshots(snaps)
        hists = [i for i in merged.instruments()
                 if i.name == "knn_predict_wall_ms"]
        assert len(hists) == 2
        for h in hists:
            assert h.count == 3
            assert h.bucket_counts() == [1, 1, 1, 0]

    def test_merge_into_shared_registry_twice_accumulates_counters(self):
        reg = MetricsRegistry()
        snap = aggregate.snapshot_registry(make_proc_registry(0, {}))
        aggregate.merge_snapshots({0: snap}, registry=reg)
        aggregate.merge_snapshots({0: snap}, registry=reg)
        c = [i for i in reg.instruments()
             if i.name == "knn_predict_calls_total"][0]
        assert c.value == 20  # counters add; the caller owns merge cadence

    def test_unknown_kind_raises(self):
        with pytest.raises(ValueError, match="unknown kind"):
            aggregate.merge_snapshots({0: [{
                "name": "x", "kind": "mystery", "labels": {}, "value": 1,
            }]})


class TestStragglers:
    def test_max_min_skew_per_path(self):
        snaps = {
            0: aggregate.snapshot_registry(
                make_proc_registry(0, {"ring": 10.0, "query-sharded": 5.0})),
            1: aggregate.snapshot_registry(
                make_proc_registry(1, {"ring": 40.0, "query-sharded": 5.0})),
        }
        merged = aggregate.merge_snapshots(snaps)
        out = aggregate.straggler_gauges(snaps, merged)
        assert out["ring"] == {
            "max_ms": 40.0, "min_ms": 10.0, "skew": 4.0, "max_proc": 1,
            "procs": 2,
        }
        assert out["query-sharded"]["skew"] == 1.0
        gauges = {
            (i.name, dict(i.labels)["path"]): i.value
            for i in merged.instruments()
            if i.name.startswith("knn_shard_dispatch_")
            and "proc" not in dict(i.labels)
        }
        assert gauges[("knn_shard_dispatch_ms_max", "ring")] == 40.0
        assert gauges[("knn_shard_dispatch_ms_min", "ring")] == 10.0
        assert gauges[("knn_shard_dispatch_skew", "ring")] == 4.0

    def test_zero_min_stays_finite(self):
        # The gauge rounds walls to 3 decimals, so a sub-µs wall stores
        # 0.0 — the skew must clamp to the rounding floor (finite, JSON-
        # safe), never float('inf').
        import json
        import math

        snaps = {
            0: aggregate.snapshot_registry(
                make_proc_registry(0, {"ring": 0.0})),
            1: aggregate.snapshot_registry(
                make_proc_registry(1, {"ring": 2.0})),
        }
        merged = aggregate.merge_snapshots(snaps)
        out = aggregate.straggler_gauges(snaps, merged)
        assert math.isfinite(out["ring"]["skew"])
        assert out["ring"]["skew"] == 2.0 / 0.001
        json.loads(json.dumps(out, allow_nan=False))  # strict-JSON safe
        both_zero = {
            0: aggregate.snapshot_registry(
                make_proc_registry(0, {"ring": 0.0})),
        }
        merged2 = aggregate.merge_snapshots(both_zero)
        assert aggregate.straggler_gauges(
            both_zero, merged2)["ring"]["skew"] == 1.0

    def test_paths_without_dispatch_absent(self):
        snaps = {0: aggregate.snapshot_registry(make_proc_registry(0, {}))}
        merged = aggregate.merge_snapshots(snaps)
        assert aggregate.straggler_gauges(snaps, merged) == {}


class TestSingleProcessAggregate:
    def test_aggregate_multihost_solo(self, global_obs):
        obs.gauge_set("knn_shard_dispatch_ms", 7.0, path="ring")
        merged, stragglers = aggregate.aggregate_multihost()
        assert merged is not None
        assert stragglers["ring"]["procs"] == 1
        procs = {dict(i.labels).get("proc") for i in merged.instruments()
                 if i.name == "knn_shard_dispatch_ms"}
        assert procs == {"0"}


class TestStrategiesRecordDispatchGauge:
    """Each sharded strategy must feed the straggler signal."""

    @pytest.fixture()
    def toy(self, rng):
        tx = rng.random((64, 7), np.float32)
        ty = rng.integers(0, 3, 64).astype(np.int32)
        qx = rng.random((16, 7), np.float32)
        return tx, ty, qx

    def _gauge_paths(self):
        return {
            dict(i.labels)["path"]
            for i in obs.registry().instruments()
            if i.name == "knn_shard_dispatch_ms"
        }

    def test_query_sharded(self, global_obs, toy):
        from knn_tpu.parallel.query_sharded import predict_query_sharded

        tx, ty, qx = toy
        predict_query_sharded(tx, ty, qx, 3, 3, num_devices=2, engine="xla")
        assert "query-sharded" in self._gauge_paths()

    def test_train_sharded(self, global_obs, toy):
        from knn_tpu.parallel.train_sharded import predict_train_sharded

        tx, ty, qx = toy
        predict_train_sharded(tx, ty, qx, 3, 3, num_devices=2,
                              mesh_shape=(1, 2), engine="xla")
        assert "train-sharded" in self._gauge_paths()

    def test_ring(self, global_obs, toy):
        from knn_tpu.parallel.ring import predict_ring

        tx, ty, qx = toy
        predict_ring(tx, ty, qx, 3, 3, num_devices=2, engine="full")
        assert "ring" in self._gauge_paths()
