"""Async retrieval/predict surface (VERDICT r4 #6).

``kneighbors_async`` / ``predict_async`` return :class:`AsyncResult`
handles whose device work is dispatched before the call returns; resolving
must give bit-identical results to the synchronous methods — on every
engine, on multi-chunk query sets, for both model families, and for the
weighted vote. The round-trip amortization itself is measured in
bench.py's kneighbors config (pipelined_ms_per_call); here we pin
correctness and the handle contract.
"""

import numpy as np
import pytest

from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import AsyncResult, KNNClassifier, KNNRegressor


def _problem(rng, n=400, q=50, d=5, c=6):
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)  # grid -> ties
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, q // 2, replace=False)],
         rng.integers(0, 4, (q - q // 2, d)).astype(np.float32)]
    )
    train = Dataset(train_x, train_y)
    test = Dataset(test_x, np.zeros(len(test_x), np.int32))
    return train, test


class TestKneighborsAsync:
    @pytest.mark.parametrize("engine", ["xla", "stripe"])
    def test_matches_sync(self, rng, engine):
        train, test = _problem(rng)
        model = KNNClassifier(k=5, engine=engine).fit(train)
        want_d, want_i = model.kneighbors(test)
        got_d, got_i = model.kneighbors_async(test).result()
        np.testing.assert_array_equal(got_i, want_i)
        np.testing.assert_array_equal(got_d, want_d)

    def test_result_memoized_and_interleaved(self, rng):
        # Several handles in flight at once resolve independently and
        # repeat .result() calls return the same arrays without re-fetching.
        train, test = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        want = model.kneighbors(test)
        handles = [model.kneighbors_async(test) for _ in range(4)]
        for h in reversed(handles):  # resolve out of dispatch order
            d, i = h.result()
            np.testing.assert_array_equal(i, want[1])
        first = handles[0].result()
        assert first is handles[0].result()  # memoized, no second sync

    def test_multi_chunk_matches_sync(self, rng):
        # Query set spanning several dispatch chunks: the deferred windowed
        # path must still drain in order and concatenate correctly. block_q
        # is forced small so chunk_rows=64 really yields multiple chunks
        # (with the default block_q, 320 queries resolve to one chunk and
        # the multi-chunk drain/trim logic would go untested), and q is NOT
        # a chunk multiple so the device-side row pad + tail trim runs.
        train, test = _problem(rng, n=256, q=40)
        big = Dataset(
            np.tile(test.features, (8, 1))[:301],
            np.zeros(301, np.int32),
        )
        model = KNNClassifier(k=4, engine="stripe").fit(train)
        want_d, want_i = model.kneighbors(big)
        # chunk_rows is not plumbed through the model API; go through the
        # op entry to force chunking with a deferred resolve.
        from knn_tpu.ops.pallas_knn import stripe_candidates_arrays

        resolve = stripe_candidates_arrays(
            train.features, big.features, 4, block_q=8, chunk_rows=64,
            deferred=True,
        )
        got_d, got_i = resolve()
        assert got_d.shape == want_d.shape == (301, 4)
        np.testing.assert_array_equal(got_i, want_i)
        np.testing.assert_array_equal(got_d, want_d)
        # Repeat resolve returns the memoized result, not a re-drain.
        again = resolve()
        np.testing.assert_array_equal(again[1], got_i)

    def test_regressor_matches_sync(self, rng):
        train, test = _problem(rng)
        reg_train = Dataset(
            train.features, train.labels,
            raw_targets=rng.standard_normal(train.num_instances).astype(
                np.float32),
        )
        model = KNNRegressor(k=5, weights="distance").fit(reg_train)
        want_d, want_i = model.kneighbors(test)
        got_d, got_i = model.kneighbors_async(test).result()
        np.testing.assert_array_equal(got_i, want_i)
        np.testing.assert_array_equal(got_d, want_d)
        np.testing.assert_array_equal(
            model.predict_async(test).result(), model.predict(test)
        )


class TestPredictAsync:
    @pytest.mark.parametrize("weights", ["uniform", "distance"])
    def test_matches_sync(self, rng, weights):
        train, test = _problem(rng)
        model = KNNClassifier(k=5, weights=weights).fit(train)
        np.testing.assert_array_equal(
            model.predict_async(test).result(), model.predict(test)
        )

    def test_matches_oracle_backend_predictions(self, rng):
        # predict_async rides the candidate kernel regardless of the fitted
        # backend; the tie contracts make that identical to any exact
        # backend's predictions — pin against the oracle.
        train, test = _problem(rng)
        async_preds = KNNClassifier(k=5).fit(train).predict_async(test).result()
        oracle = KNNClassifier(k=5, backend="oracle").fit(train).predict(test)
        np.testing.assert_array_equal(async_preds, oracle)

    def test_requires_fit(self, rng):
        _, test = _problem(rng)
        with pytest.raises(RuntimeError, match="fit"):
            KNNClassifier(k=5).predict_async(test)

    def test_handle_type(self, rng):
        train, test = _problem(rng)
        model = KNNClassifier(k=5).fit(train)
        assert isinstance(model.predict_async(test), AsyncResult)
        assert isinstance(model.kneighbors_async(test), AsyncResult)
