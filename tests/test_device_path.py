"""The device-resident retrieval hot path (ROADMAP item 2).

Pins the three tentpole pieces and their satellites:

- the device IVF gather+score kernel (``ops/segment_score.py``) is
  BIT-identical to the host scorer — same distances, same indices —
  across k/nprobe/ties/NaN/dtypes, and the ``nprobe == num_cells``
  device path reproduces ``oracle_kneighbors`` exactly (the acceptance
  pin);
- ``lax.approx_max_k`` centroid ranking arms past the cell threshold,
  never touches the full-probe bit-identity anchor, and its answers
  stay honest under the shadow scorer's recall-floor machinery;
- the device-resident delta tail (``mutable/device_tail.py``) grows by
  doubling with append-frozen snapshots, merges bit-identically to the
  host merge on every path that fuses (and falls back to the host merge
  where documented), and survives concurrent mutation;
- incremental IVF compaction assigns folded rows to existing cells and
  records which branch ran; delete-aware probe accounting feeds live
  tombstone counts into the k-coverage widening.
"""

from __future__ import annotations

import threading
import time

import numpy as np
import pytest

from knn_tpu.backends.oracle import oracle_kneighbors
from knn_tpu.data.dataset import Dataset
from knn_tpu.index.ivf import (
    IVF_ATTR,
    IVFIndex,
    IVFServing,
)
from knn_tpu.models.knn import (
    DEFAULT_CANDIDATE_BUCKETS,
    KNNClassifier,
    KNNRegressor,
    candidate_padded_rows,
)
from knn_tpu.mutable.engine import MutableEngine
from knn_tpu.mutable.state import merged_oracle_kneighbors
from knn_tpu.serve.artifact import save_index
from knn_tpu.serve.batcher import MicroBatcher


@pytest.fixture
def rng():
    return np.random.default_rng(7)


def _tie_problem(rng, n=400, d=6, q=24):
    """Grid-valued features -> plentiful exact distance ties, plus an
    exact-match query and a NaN query (the adversarial corners)."""
    x = rng.integers(0, 4, (n, d)).astype(np.float32)
    x[40:50] = x[0:10]  # duplicate rows: exact ties across cells
    qx = rng.integers(0, 4, (q, d)).astype(np.float32)
    qx[1] = x[17]       # exact match (distance 0 ties)
    qx[3, 2] = np.nan   # NaN query -> all +inf, ties broken by index
    return x, qx


def _assert_bitwise(a, b, what=""):
    d1, i1 = a
    d2, i2 = b
    np.testing.assert_array_equal(i1, i2, err_msg=f"{what}: indices")
    assert (np.asarray(d1, np.float32).view(np.uint32)
            == np.asarray(d2, np.float32).view(np.uint32)).all(), \
        f"{what}: distances not bit-identical"


class TestDeviceScorerBitIdentity:
    def test_matrix_vs_host(self, rng):
        x, qx = _tie_problem(rng)
        ivf = IVFIndex.build(x, 16, seed=0)
        for k, nprobe in [(1, 1), (5, 2), (5, 16), (10, 3), (64, 5)]:
            host = ivf.search(x, qx, k, nprobe, scorer="host")
            dev = ivf.search(x, qx, k, nprobe, scorer="device")
            _assert_bitwise(host[:2], dev[:2], f"k={k} nprobe={nprobe}")
            assert host[2].scorer == "host"
            assert dev[2].scorer == "device"
            assert dev[2].padded_candidate_rows >= 0

    def test_full_probe_device_bit_identical_to_oracle(self, rng):
        x, qx = _tie_problem(rng)
        ivf = IVFIndex.build(x, 16, seed=0)
        for k in (1, 5, 17):
            od, oi = oracle_kneighbors(x, qx, k)
            dd, di, st = ivf.search(x, qx, k, 16, scorer="device")
            _assert_bitwise((od, oi), (dd, di), f"oracle k={k}")
            assert st.scorer == "device"

    def test_wide_dtype_queries_coerce(self, rng):
        x, qx = _tie_problem(rng)
        ivf = IVFIndex.build(x, 8, seed=0)
        host = ivf.search(x, qx.astype(np.float64), 5, 3, scorer="host")
        dev = ivf.search(x.astype(np.float64), qx, 5, 3, scorer="device")
        _assert_bitwise(host[:2], dev[:2], "dtype coercion")

    def test_k_exceeds_candidates_pads_with_sentinel(self, rng):
        x, qx = _tie_problem(rng, n=60)
        ivf = IVFIndex.build(x, 30, seed=0)
        # k near n forces widening to everything; both scorers must agree
        # on the (inf, sentinel) padding rows too.
        host = ivf.search(x, qx, 59, 1, scorer="host")
        dev = ivf.search(x, qx, 59, 1, scorer="device")
        _assert_bitwise(host[:2], dev[:2], "k-coverage saturation")

    def test_auto_routes_small_to_host_env_overrides(self, rng, monkeypatch):
        x, qx = _tie_problem(rng, n=120, q=4)
        ivf = IVFIndex.build(x, 8, seed=0)
        st = ivf.search(x, qx, 3, 2)[2]
        assert st.scorer == "host"  # tiny workload: auto stays host
        monkeypatch.setenv("KNN_TPU_IVF_SCORER", "device")
        st = ivf.search(x, qx, 3, 2)[2]
        assert st.scorer == "device"
        monkeypatch.setenv("KNN_TPU_IVF_SCORER", "host")
        st = ivf.search(x, qx, 3, 2, scorer="device")[2]
        assert st.scorer == "device"  # explicit arg beats the env

    def test_serving_rung_device_scorer_bit_identity(self, rng):
        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=5, engine="xla").fit(Dataset(x, y))
        setattr(model, IVF_ATTR, IVFIndex.build(x, 16, seed=0))
        want = model.ivf_.search(x, qx, 5, 4, scorer="host")[:2]
        serving = IVFServing(4, 16, scorer="device")
        got = serving.kneighbors(model, qx)
        _assert_bitwise(want, got, "serving rung")

    def test_candidate_bucket_one_definition(self):
        from knn_tpu.obs import accounting as acct

        assert candidate_padded_rows(0) == 0
        assert candidate_padded_rows(1) == DEFAULT_CANDIDATE_BUCKETS[0]
        for b in DEFAULT_CANDIDATE_BUCKETS:
            assert candidate_padded_rows(b) == b
            assert candidate_padded_rows(b - 1) == b
        top = DEFAULT_CANDIDATE_BUCKETS[-1]
        assert candidate_padded_rows(top + 1) == 2 * top
        # The accounting twin resolves through the SAME definition.
        for m in (1, 300, 5000, top + 9):
            assert acct.padded_candidate_rows(m) == candidate_padded_rows(m)


class TestApproxCentroidRanking:
    def test_arms_past_threshold_only(self, rng, monkeypatch):
        x, qx = _tie_problem(rng)
        ivf = IVFIndex.build(x, 16, seed=0)
        assert ivf.search(x, qx, 5, 4)[2].ranking == "exact"
        monkeypatch.setenv("KNN_TPU_IVF_APPROX_CELLS", "8")
        assert ivf.search(x, qx, 5, 4)[2].ranking == "approx"
        # Full probe NEVER rides approx ranking: the bit-identity anchor.
        dd, di, st = ivf.search(x, qx, 5, 16, scorer="device")
        assert st.ranking == "exact"
        _assert_bitwise(oracle_kneighbors(x, qx, 5), (dd, di),
                        "full probe under approx threshold")

    def test_approx_answers_carry_exact_distances(self, rng, monkeypatch):
        """The approx rung's promise: ranking is approximate, every
        returned candidate's distance is exact — the shadow scorer's
        recomputed-distance admissibility check must stay silent."""
        monkeypatch.setenv("KNN_TPU_IVF_APPROX_CELLS", "8")
        x, qx = _tie_problem(rng)
        ivf = IVFIndex.build(x, 16, seed=0)
        d, i, st = ivf.search(x, qx, 5, 4)
        assert st.ranking == "approx"
        finite = np.isfinite(d)
        diff = qx[:, None, :] - x[i]
        true_d = np.einsum("qkd,qkd->qk", diff, diff, dtype=np.float32)
        np.testing.assert_array_equal(d[finite], true_d[finite])

    def test_recall_floor_machinery_scores_approx_rung(self, rng,
                                                       monkeypatch):
        from knn_tpu.obs import quality as q

        monkeypatch.setenv("KNN_TPU_IVF_APPROX_CELLS", "8")
        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=5, engine="xla").fit(Dataset(x, y))
        ivf = IVFIndex.build(x, 16, seed=0)
        setattr(model, IVF_ATTR, ivf)
        verdicts = []

        class SpySLO:
            def record_quality(self, good):
                verdicts.append(good)

        scorer = q.ShadowScorer(1.0, seed=0, slo=SpySLO(),
                                approx_floors={"ivf": 0.5},
                                autostart=False)
        d, i, st = ivf.search(x, qx, 5, 4)
        assert st.ranking == "approx"
        assert scorer.offer(features=qx, kind="kneighbors", dists=d,
                            idx=i, preds=None, rung="ivf", model=model,
                            version="v1")
        scorer._sq.start()
        assert scorer.drain(30)
        # approx ranking holds recall above this generous floor here,
        # and the answers carry honest exact distances -> good verdict,
        # no distance divergence.
        assert verdicts[-1] is True
        rungs = scorer.export()["rungs"]
        assert not rungs["ivf"]["divergence"].get("distance")


def _mutable_pair(model, tmp_path, **kw):
    """Two engines over byte-identical artifacts: device tail forced on
    vs off — the merged-serving bit-identity harness."""
    import shutil

    root_on = tmp_path / "idx-on"
    save_index(model, root_on, ivf=getattr(model, IVF_ATTR, None))
    root_off = tmp_path / "idx-off"
    shutil.copytree(root_on, root_off)
    on = MutableEngine(model, root_on, delta_cap=256,
                       device_tail="on", **kw)
    off = MutableEngine(model, root_off, delta_cap=256,
                        device_tail="off", **kw)
    return on, off


class TestDeviceDeltaTail:
    def test_lazy_activation_and_modes(self, rng, tmp_path):
        x, _ = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=3, engine="xla").fit(Dataset(x, y))
        on, off = _mutable_pair(model, tmp_path)
        assert on.snapshot().device is None  # lazy: nothing inserted yet
        on.apply_insert(x[:2], y[:2].astype(np.float32),
                        time.monotonic_ns())
        tv = on.snapshot().device
        assert tv is not None and tv.count == 2 and tv.base_n == x.shape[0]
        off.apply_insert(x[:2], y[:2].astype(np.float32),
                         time.monotonic_ns())
        assert off.snapshot().device is None  # off: never constructs
        doc = on.export()
        assert doc["device_tail"] == {"mode": "on", "active": True}

    def test_auto_threshold_activation(self, rng, tmp_path, monkeypatch):
        from knn_tpu.mutable import engine as eng_mod

        monkeypatch.setattr(eng_mod, "DEVICE_TAIL_MIN_ROWS", 8)
        x, _ = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=3, engine="xla").fit(Dataset(x, y))
        root = tmp_path / "idx"
        save_index(model, root)
        eng = MutableEngine(model, root, delta_cap=256)
        eng.apply_insert(x[:4], y[:4].astype(np.float32),
                         time.monotonic_ns())
        assert eng.snapshot().device is None  # below the threshold
        eng.apply_insert(x[4:12], y[4:12].astype(np.float32),
                         time.monotonic_ns())
        assert eng.snapshot().device is not None

    def test_growth_keeps_snapshots_frozen(self, rng, tmp_path):
        x, _ = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=3, engine="xla").fit(Dataset(x, y))
        on, _off = _mutable_pair(model, tmp_path)
        rows = rng.standard_normal((20, x.shape[1])).astype(np.float32)
        on.apply_insert(rows, rng.integers(0, 3, 20).astype(np.float32),
                        time.monotonic_ns())
        view = on.snapshot()
        tv = view.device
        frozen = np.asarray(tv.features)[:tv.count].copy()
        # Grow past several doublings (64 -> 256 host slots).
        more = rng.standard_normal((200, x.shape[1])).astype(np.float32)
        on.apply_insert(more, rng.integers(0, 3, 200).astype(np.float32),
                        time.monotonic_ns())
        np.testing.assert_array_equal(
            np.asarray(tv.features)[:tv.count], frozen)
        v2 = on.snapshot()
        np.testing.assert_array_equal(
            np.asarray(v2.device.features)[:v2.count],
            np.asarray(v2.features)[:v2.count])

    def test_merged_serving_bit_identity_both_families(self, rng,
                                                       tmp_path):
        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        for family in ("classifier", "regressor"):
            if family == "classifier":
                model = KNNClassifier(k=5, engine="xla").fit(
                    Dataset(x, y))
            else:
                model = KNNRegressor(k=5, engine="xla").fit(Dataset(x, y))
            on, off = _mutable_pair(model, tmp_path / family)
            rows = rng.standard_normal((30, x.shape[1])).astype(
                np.float32)
            vals = rng.integers(0, 3, 30).astype(np.float32)
            for e in (on, off):
                e.apply_insert(rows, vals, time.monotonic_ns())
            b_on = MicroBatcher(model, max_batch=64, max_wait_ms=0.0,
                                mutable=on)
            b_off = MicroBatcher(model, max_batch=64, max_wait_ms=0.0,
                                 mutable=off)
            try:
                _assert_bitwise(b_off.kneighbors(qx, timeout=60),
                                b_on.kneighbors(qx, timeout=60),
                                f"{family} insert-only")
                np.testing.assert_array_equal(
                    b_on.predict(qx, timeout=60),
                    b_off.predict(qx, timeout=60))
                # delta delete: fused path masks the dead slot
                for b in (b_on, b_off):
                    b.submit_mutation(
                        "delete", {"ids": [x.shape[0] + 1]}).result(
                        timeout=60)
                d1, i1 = b_on.kneighbors(qx, timeout=60)
                _assert_bitwise(b_off.kneighbors(qx, timeout=60),
                                (d1, i1), f"{family} delta delete")
                assert not (i1 == x.shape[0] + 1).any()
                # base tombstone: documented host-merge fallback, still
                # bit-identical end to end
                for b in (b_on, b_off):
                    b.submit_mutation("delete", {"ids": [17]}).result(
                        timeout=60)
                d1, i1 = b_on.kneighbors(qx, timeout=60)
                _assert_bitwise(b_off.kneighbors(qx, timeout=60),
                                (d1, i1), f"{family} base tombstone")
                assert not (i1 == 17).any()
                want = merged_oracle_kneighbors(model, on.snapshot(), qx)
                np.testing.assert_array_equal(i1, want[1])
            finally:
                b_on.close()
                b_off.close()

    def test_ivf_rung_fused_delta_bit_identity(self, rng, tmp_path):
        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=4, engine="xla").fit(Dataset(x, y))
        setattr(model, IVF_ATTR, IVFIndex.build(x, 12, seed=0))
        on, off = _mutable_pair(model, tmp_path)
        rows = rng.standard_normal((30, x.shape[1])).astype(np.float32)
        for e in (on, off):
            e.apply_insert(rows, rng.integers(0, 3, 30).astype(
                np.float32), time.monotonic_ns())
        serving = IVFServing(4, 12)
        got = serving.kneighbors(model, qx, view=on.snapshot())
        want = serving.kneighbors(model, qx, view=off.snapshot())
        _assert_bitwise(want, got, "ivf fused delta")
        # fused stats really rode the device
        st = model.ivf_.search_merged(
            x, qx, 4, 4, on.snapshot())[2]
        assert st.merged_delta and st.scorer == "device"

    def test_concurrent_mutation_vs_reads(self, rng, tmp_path):
        """Readers race a writer thread: every response must be
        internally consistent (bit-equal to the merged oracle at ITS
        view), and the device tail must never tear."""
        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=4, engine="xla").fit(Dataset(x, y))
        on, _off = _mutable_pair(model, tmp_path)
        b = MicroBatcher(model, max_batch=64, max_wait_ms=0.0, mutable=on)
        stop = threading.Event()
        errors = []

        def writer():
            i = 0
            while not stop.is_set() and i < 40:
                rows = rng.standard_normal((3, x.shape[1])).astype(
                    np.float32)
                try:
                    b.submit_mutation("insert", {
                        "rows": rows,
                        "values": rng.integers(0, 3, 3).astype(
                            np.float32),
                    }).result(timeout=30)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(15):
                d, i = b.kneighbors(qx, timeout=60)
                assert d.shape == (qx.shape[0], 4)
                # rows sorted ascending wherever finite (the NaN query's
                # all-inf row has no meaningful diff)
                with np.errstate(invalid="ignore"):
                    steps = np.diff(d, axis=1)
                ok = np.isfinite(steps)
                assert (steps[ok] >= 0).all()
        finally:
            stop.set()
            t.join(30)
            b.close()
        assert not errors
        view = on.snapshot()
        want = merged_oracle_kneighbors(model, view, qx)
        got = merged_oracle_kneighbors(model, view, qx)
        np.testing.assert_array_equal(got[1], want[1])


class TestIncrementalCompaction:
    def _compact_once(self, model, engine):
        from knn_tpu.mutable.compact import Compactor

        def swap(m, v, hook):
            hook()
            return "prev"

        c = Compactor(engine, swap=swap, warm=lambda m: None,
                      threshold=10_000, interval_s=0)
        return c.run_once()

    def test_incremental_path_and_forced_rebuild(self, rng, tmp_path,
                                                 monkeypatch):
        x, _ = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=4, engine="xla").fit(Dataset(x, y))
        setattr(model, IVF_ATTR, IVFIndex.build(x, 12, seed=0))
        root = tmp_path / "idx"
        save_index(model, root, ivf=model.ivf_)
        eng = MutableEngine(model, root, delta_cap=256,
                            device_tail="off")
        eng.apply_insert(
            x[:20] + 0.25, rng.integers(0, 3, 20).astype(np.float32),
            time.monotonic_ns())
        out = self._compact_once(model, eng)
        assert out["compacted"] and out["ivf_compaction"] == "incremental"
        assert out["ivf_cell_imbalance"] >= 1.0
        # a zero imbalance budget forces the full Lloyd's rebuild
        monkeypatch.setenv("KNN_TPU_IVF_REBUILD_IMBALANCE", "0")
        eng.apply_insert(
            x[:5] + 0.5, rng.integers(0, 3, 5).astype(np.float32),
            time.monotonic_ns())
        out2 = self._compact_once(model, eng)
        assert out2["compacted"] and out2["ivf_compaction"] == "rebuild"

    def test_assign_to_is_deterministic_and_keeps_centroids(self, rng):
        x, _ = _tie_problem(rng)
        base = IVFIndex.build(x, 10, seed=3)
        extra = np.concatenate([x, x[:13] + 0.125])
        a = IVFIndex.assign_to(extra, base)
        b = IVFIndex.assign_to(extra, base)
        np.testing.assert_array_equal(a.row_perm, b.row_perm)
        np.testing.assert_array_equal(a.centroids, base.centroids)
        assert a.meta["incremental"] and a.num_rows == extra.shape[0]
        # the incremental partition still serves exactly
        od, oi = oracle_kneighbors(extra, x[:8], 5)
        dd, di, _ = a.search(extra, x[:8], 5, 10)
        _assert_bitwise((od, oi), (dd, di), "incremental full probe")


class TestDeleteAwareProbeAccounting:
    def test_dead_rows_per_cell(self, rng):
        x, _ = _tie_problem(rng)
        ivf = IVFIndex.build(x, 8, seed=0)
        inv = np.empty(x.shape[0], np.int64)
        inv[ivf.row_perm] = np.arange(x.shape[0])
        dead = np.array([0, 5, 9, 200], np.int64)
        got = ivf.dead_rows_per_cell(dead)
        want = np.zeros(8, np.int64)
        for r in dead:
            cell = int(np.searchsorted(ivf.cell_offsets, inv[r],
                                       side="right") - 1)
            want[cell] += 1
        np.testing.assert_array_equal(got, want)
        assert got.sum() == dead.size

    def test_live_coverage_widens_past_dead_cells(self, rng):
        """A probed cell whose rows are all tombstoned must not satisfy
        k-coverage: the widening math counts LIVE rows only, so results
        never come up short of live candidates."""
        x, qx = _tie_problem(rng, n=120)
        ivf = IVFIndex.build(x, 6, seed=0)
        sizes = ivf.cell_sizes
        # tombstone every row of the largest cell
        cell = int(np.argmax(sizes))
        lo, hi = int(ivf.cell_offsets[cell]), int(ivf.cell_offsets[
            cell + 1])
        dead_rows = ivf.row_perm[lo:hi]
        dead_per_cell = ivf.dead_rows_per_cell(dead_rows)
        k = 5
        d_naive, i_naive, st_naive = ivf.search(x, qx, k, 1)
        d, i, st = ivf.search(x, qx, k, 1, dead_per_cell=dead_per_cell)
        assert st.dead_rows >= 0
        live = ~np.isin(i, dead_rows)
        # after masking the dead rows, every query still has k live
        # candidates available among the returned set's live portion
        # only if coverage counted live rows; the naive search can
        # return rows that are all dead for queries centred on the
        # dead cell.
        assert st.forced_widenings >= st_naive.forced_widenings
        assert (np.isin(i, dead_rows).sum(axis=1) + live.sum(axis=1)
                == k).all()
        # the live-coverage guarantee: at least k live candidates were
        # gathered for every query (the probe set widened past the dead
        # cell), so a post-merge mask can always fill top-k.
        live_sizes = sizes - dead_per_cell
        sel_counts = st.candidate_rows - st.dead_rows
        assert sel_counts >= k * qx.shape[0] or (
            live_sizes.sum() < k)

    def test_serving_records_dead_candidate_counter(self, rng, tmp_path):
        from knn_tpu import obs

        x, qx = _tie_problem(rng)
        y = rng.integers(0, 3, x.shape[0]).astype(np.int32)
        model = KNNClassifier(k=4, engine="xla").fit(Dataset(x, y))
        setattr(model, IVF_ATTR, IVFIndex.build(x, 12, seed=0))
        on, _off = _mutable_pair(model, tmp_path)
        on.apply_insert(x[:8] + 0.5,
                        rng.integers(0, 3, 8).astype(np.float32),
                        time.monotonic_ns())
        on.apply_delete([11, 23], time.monotonic_ns())
        serving = IVFServing(4, 12)
        obs.enable()
        try:
            obs.reset()
            serving.kneighbors(model, qx, view=on.snapshot())
            metrics = {i.name for i in obs.registry().instruments()}
            assert "knn_ivf_dead_candidate_rows_total" in metrics
            assert "knn_ivf_scorer_dispatch_total" in metrics
        finally:
            obs.disable()
