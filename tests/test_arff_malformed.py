"""Malformed-ARFF hardening (ISSUE 2 satellite): truncated files,
non-numeric fields, and unknown class labels raise ``DataError`` with
file/line context — from BOTH parsers (pure-Python and native C++), never
a crash, segfault, or untyped traceback."""

import numpy as np
import pytest

from knn_tpu.data import pyarff
from knn_tpu.resilience.errors import DataError


def _parsers():
    out = [("py", pyarff.parse_arff_file)]
    try:
        from knn_tpu.native import arff_native

        out.append(("cc", arff_native.parse))
    except (ImportError, OSError):
        pass
    return out


PARSERS = _parsers()


def _write(tmp_path, content, name="bad.arff"):
    p = tmp_path / name
    p.write_text(content)
    return str(p)


HEADER = "@relation r\n@attribute x NUMERIC\n@attribute class {a,b}\n"

# (case name, file content, required message fragment, required :line:)
MALFORMED = [
    ("empty_file", "", "no @attribute", None),
    ("truncated_mid_attribute", "@relation r\n@attribute x {a,",
     "unterminated nominal value list", ":2:"),
    ("truncated_mid_quote", HEADER + "@data\n1,'a\n",
     "unterminated quoted value", ":5:"),
    ("non_numeric_field", HEADER + "@data\nfoo,a\n",
     "cannot parse 'foo' as a number for 'x'", ":5:"),
    ("unknown_class_label", HEADER + "@data\n1,zz\n",
     "value 'zz' not in nominal set for 'class'", ":5:"),
    ("missing_class_label",
     "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
     "@data\n1,?\n",
     "missing class label", None),
    ("data_before_header", "@relation r\n1,2\n",
     "before", ":2:"),
    ("binary_garbage", "\x01\x02\x7f\x00broken\x00\n@@@\n",
     "", None),  # any located DataError is acceptable for random bytes
]


class TestMalformedFixtures:
    @pytest.mark.parametrize("parser_name,parse", PARSERS)
    @pytest.mark.parametrize(
        "case,content,fragment,line", MALFORMED,
        ids=[m[0] for m in MALFORMED],
    )
    def test_raises_located_data_error(
        self, tmp_path, parser_name, parse, case, content, fragment, line
    ):
        path = _write(tmp_path, content, f"{case}.arff")
        with pytest.raises(DataError) as ei:
            parse(path)
        msg = str(ei.value)
        assert path.split("/")[-1] in msg, f"no file context in {msg!r}"
        if fragment:
            assert fragment in msg, (case, msg)
        if line:
            assert line in msg, f"no line context {line} in {msg!r}"

    @pytest.mark.parametrize("parser_name,parse", PARSERS)
    def test_directory_is_a_clean_error(self, tmp_path, parser_name, parse):
        with pytest.raises((DataError, OSError)):
            parse(str(tmp_path))

    @pytest.mark.parametrize("parser_name,parse", PARSERS)
    def test_missing_file_is_a_clean_error(self, parser_name, parse):
        with pytest.raises((DataError, OSError)):
            parse("/no/such/dir/no-such.arff")

    def test_load_arff_missing_file_is_data_error(self):
        # The load front-end types missing files too (the CLI's exit-2
        # message branches on DataError, not strerror text).
        from knn_tpu.data.arff import load_arff

        with pytest.raises(DataError):
            load_arff("/no/such/dir/no-such.arff")

    @pytest.mark.parametrize("parser_name,parse", PARSERS)
    def test_partial_row_at_eof_is_discarded_not_crashed(
        self, tmp_path, parser_name, parse
    ):
        # Truncation INSIDE the final row keeps the dialect's documented
        # discard rule (arff_parser.cpp:130-133) — a truncated download
        # yields the complete prefix, not a crash.
        # ",," is an empty cell -> located error even in the final row
        # (empty cells error at scan time), while missing trailing cells at
        # EOF are the discard case:
        path = _write(tmp_path, HEADER + "@data\n1,a\n2,b\n3,,\n")
        with pytest.raises(DataError):
            parse(path)
        path2 = _write(tmp_path, HEADER + "@data\n1,a\n2,b\n3\n", "p2.arff")
        ds = parse(path2)
        assert ds.num_instances == 2
        np.testing.assert_array_equal(ds.labels, [0, 1])

    def test_parsers_agree_on_error_text(self, tmp_path):
        # Both parsers cite the same location and reason, so the CLI's
        # one-line message is stable whichever parser is active.
        if len(PARSERS) < 2:
            pytest.skip("native parser not built")
        path = _write(tmp_path, HEADER + "@data\nnope,a\n")
        msgs = []
        for _, parse in PARSERS:
            with pytest.raises(DataError) as ei:
                parse(path)
            msgs.append(str(ei.value))
        assert msgs[0] == msgs[1]
