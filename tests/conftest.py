"""Test harness config.

Tests run on CPU with 8 virtual XLA devices
(``--xla_force_host_platform_device_count=8``) — the JAX-world fake backend
for shard_map/mesh tests without TPU hardware (SURVEY.md §4). Must be set
before jax is imported anywhere.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

# The environment's sitecustomize registers the axon TPU platform
# programmatically, overriding JAX_PLATFORMS from the env — force CPU back on
# via the config so tests get the 8 virtual devices.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

from tests import fixtures


@pytest.fixture(scope="session")
def small():
    return fixtures.load_pair("small")


@pytest.fixture(scope="session")
def medium():
    return fixtures.load_pair("medium")


@pytest.fixture(scope="session")
def large():
    return fixtures.load_pair("large")


@pytest.fixture(scope="session")
def small_paths():
    d = fixtures.datasets_dir()
    return str(d / "small-train.arff"), str(d / "small-test.arff")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
