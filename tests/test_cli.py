"""CLI contract tests: positional argv, personas, byte-compatible output line
(main.cpp:146), error handling (SURVEY.md §5.5-5.6)."""

import io
import json
import re

import pytest

from knn_tpu.cli import run
from tests import fixtures


@pytest.fixture(scope="module")
def paths():
    d = fixtures.datasets_dir()
    return str(d / "small-train.arff"), str(d / "small-test.arff")


# The reference's printf contract (main.cpp:146).
LINE_RE = re.compile(
    r"^The (\d+)-NN classifier for (\d+) test instances on (\d+) train instances "
    r"required (\d+) ms CPU time\. Accuracy was (\d\.\d{4})$"
)


class TestCli:
    def test_output_line_contract(self, paths):
        out = io.StringIO()
        assert run([paths[0], paths[1], "3", "--backend", "oracle"], stdout=out) == 0
        m = LINE_RE.match(out.getvalue().strip())
        assert m, f"output line does not match reference contract: {out.getvalue()!r}"
        assert m.group(1) == "3"
        assert m.group(2) == "80"
        assert m.group(3) == "592"

    @pytest.mark.skipif(
        not fixtures.using_reference_datasets(), reason="reference datasets required"
    )
    def test_small_k1_accuracy_field(self, paths):
        out = io.StringIO()
        assert run([paths[0], paths[1], "1", "--backend", "tpu"], stdout=out) == 0
        assert out.getvalue().strip().endswith("Accuracy was 0.8500")

    def test_personas_share_one_algorithm(self, paths):
        accs = []
        for persona_args in (
            ["--persona", "main"],
            ["--persona", "tpu"],
        ):
            out = io.StringIO()
            assert run([paths[0], paths[1], "5"] + persona_args, stdout=out) == 0
            accs.append(out.getvalue().strip().rsplit(" ", 1)[-1])
        assert len(set(accs)) == 1

    def test_multithread_persona_accepts_thread_count(self, paths):
        # ./multi-thread train test k numThreads (multi-thread.cpp:137).
        out = io.StringIO()
        assert (
            run([paths[0], paths[1], "5", "4", "--persona", "multi-thread"], stdout=out)
            == 0
        )
        assert LINE_RE.match(out.getvalue().splitlines()[0].strip())

    def test_json_flag(self, paths):
        out = io.StringIO()
        assert run([paths[0], paths[1], "1", "--backend", "oracle", "--json"], stdout=out) == 0
        import json

        lines = out.getvalue().strip().splitlines()
        rec = json.loads(lines[-1])
        assert rec["k"] == 1 and rec["num_test"] == 80

    def test_fallback_warns_on_stderr(self, paths, capsys, monkeypatch):
        # VERDICT r1 #5: a persona whose backend is unavailable must say so on
        # stderr (and still exit 0 with the canonical line), not silently swap.
        import knn_tpu.backends as B

        real = B.available_backends()
        monkeypatch.setattr(
            B, "available_backends", lambda: [b for b in real if b != "native"]
        )
        out = io.StringIO()
        assert run([paths[0], paths[1], "1", "--persona", "main"], stdout=out) == 0
        err = capsys.readouterr().err
        assert "warning:" in err and "'native'" in err and "'oracle'" in err
        assert LINE_RE.match(out.getvalue().strip())

    def test_unknown_backend_clean_error(self, paths, capsys):
        # A name the ladder doesn't know is a typo: usage error, exit 2.
        assert run([paths[0], paths[1], "1", "--backend", "no-such"]) == 2
        assert "unavailable" in capsys.readouterr().err

    def test_missing_file_clean_error(self, capsys):
        assert run(["/nope/train.arff", "/nope/test.arff", "1"]) == 2
        assert "error:" in capsys.readouterr().err

    def test_bad_k_clean_error(self, paths, capsys):
        assert run([paths[0], paths[1], "999999"]) == 2
        assert "exceeds" in capsys.readouterr().err

    def test_malformed_arff_clean_error(self, tmp_path, capsys):
        bad = tmp_path / "bad.arff"
        bad.write_text(
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n@data\nabc,0\n"
        )
        assert run([str(bad), str(bad), "1"]) == 2
        assert "error:" in capsys.readouterr().err


class TestSweepK:
    def test_sweep_prints_one_line_per_k(self, paths):
        out = io.StringIO()
        assert run(
            [paths[0], paths[1], "1", "--sweep-k", "5,1", "--engine", "xla"],
            stdout=out,
        ) == 0
        lines = out.getvalue().strip().splitlines()
        assert len(lines) == 2
        for line, k in zip(lines, ("1", "5")):
            m = LINE_RE.match(line)
            assert m and m.group(1) == k, line
        # Per-k accuracy must match an individual run at that k.
        single = io.StringIO()
        assert run([paths[0], paths[1], "5", "--backend", "oracle"], stdout=single) == 0
        assert lines[1].split()[-1] == single.getvalue().strip().split()[-1]

    def test_sweep_rejects_garbage(self, paths, capsys):
        assert run([paths[0], paths[1], "1", "--sweep-k", "a,b"]) == 2
        assert "positive integers" in capsys.readouterr().err

    def test_sweep_rejects_k_over_n(self, paths, capsys):
        assert run([paths[0], paths[1], "1", "--sweep-k", "1,100000"]) == 2
        assert "error:" in capsys.readouterr().err

    def test_sweep_rejects_incompatible_flags(self, paths, capsys):
        for extra in (["--approx"], ["--precision", "fast"],
                      ["--query-batch", "8"], ["--engine", "full"],
                      ["--backend", "oracle"], ["--devices", "4"],
                      ["--query-tile", "64"], ["4"]):
            assert run([paths[0], paths[1], "1", *extra, "--sweep-k", "1,5"]) == 2
            assert "incompatible" in capsys.readouterr().err


class TestExitCodes:
    """The pinned exit-code contract (docs/RESILIENCE.md): 0 success,
    2 input/usage rejected before any classification, 1 runtime failure.
    Always a one-line ``error:`` message, never a traceback."""

    def test_success_is_zero(self, paths):
        assert run([paths[0], paths[1], "1", "--backend", "oracle"],
                   stdout=io.StringIO()) == 0

    def test_k_below_one_exits_2(self, paths, capsys):
        assert run([paths[0], paths[1], "0"]) == 2
        assert "k must be >= 1" in capsys.readouterr().err

    def test_k_over_n_train_exits_2(self, paths, capsys):
        assert run([paths[0], paths[1], "999999"]) == 2
        err = capsys.readouterr().err
        assert err.startswith("error:") and len(err.strip().splitlines()) == 1

    def test_missing_train_file_exits_2(self, paths, capsys):
        assert run(["/no/such/train.arff", paths[1], "1"]) == 2
        err = capsys.readouterr().err
        assert err.startswith("error:") and "Traceback" not in err

    def test_unknown_flag_exits_2(self, paths, capsys):
        assert run([paths[0], paths[1], "1", "--bogus-flag"]) == 2

    def test_no_fallback_with_unavailable_backend_exits_2(
        self, paths, capsys, monkeypatch
    ):
        # The contradictory-flags case: asking for a backend that is not
        # registered AND forbidding the ladder from substituting one.
        import knn_tpu.backends as B

        real = B.available_backends()
        monkeypatch.setattr(
            B, "available_backends", lambda: [b for b in real if b != "native"]
        )
        assert run([paths[0], paths[1], "1", "--persona", "main",
                    "--no-fallback"]) == 2
        err = capsys.readouterr().err
        assert "--no-fallback" in err and err.startswith("error:")

    def test_recall_target_without_approx_exits_2(self, paths, capsys):
        assert run([paths[0], paths[1], "1", "--recall-target", "0.9"]) == 2
        assert "--approx" in capsys.readouterr().err

    def test_runtime_failure_exits_1_with_typed_error(
        self, paths, capsys, monkeypatch
    ):
        # A persistent fault with the ladder disabled is a runtime failure:
        # exit 1 and the typed class name on one line.
        monkeypatch.setenv("KNN_TPU_FAULTS", "backend.compile=always")
        monkeypatch.setenv("KNN_TPU_RETRY_BASE_MS", "0")
        try:
            assert run([paths[0], paths[1], "1", "--backend", "tpu",
                        "--no-fallback"], stdout=io.StringIO()) == 1
        finally:
            from knn_tpu.resilience import faults

            monkeypatch.delenv("KNN_TPU_FAULTS")
            faults.install_from_env()
        err = capsys.readouterr().err
        assert "CompileError" in err and "Traceback" not in err


class TestSubcommands:
    """The subcommand layer (serve subsystem PR): bare positional argv
    still routes to classify byte-compatibly; `save-index` and `serve`
    exist with the pinned exit-code contract."""

    def test_explicit_classify_matches_default(self, paths):
        plain, explicit = io.StringIO(), io.StringIO()
        assert run([paths[0], paths[1], "3", "--backend", "oracle"],
                   stdout=plain) == 0
        assert run(["classify", paths[0], paths[1], "3", "--backend",
                    "oracle"], stdout=explicit) == 0
        normalize = lambda s: re.sub(r"required \d+ ms", "required N ms", s)  # noqa: E731
        assert normalize(plain.getvalue()) == normalize(explicit.getvalue())

    def test_save_index_then_serve_load(self, paths, tmp_path):
        import numpy as np

        from knn_tpu.data.arff import load_arff
        from knn_tpu.models.knn import KNNClassifier
        from knn_tpu.serve.artifact import load_index

        out = io.StringIO()
        index = tmp_path / "idx"
        assert run(["save-index", paths[0], str(index), "--k", "3"],
                   stdout=out) == 0
        assert "wrote index" in out.getvalue()
        loaded = load_index(index)
        train, test = load_arff(paths[0]), load_arff(paths[1])
        np.testing.assert_array_equal(
            loaded.predict(test), KNNClassifier(k=3).fit(train).predict(test)
        )


class TestServeExitCodes:
    """2 = bad serve/artifact args rejected before any compute; the same
    contract TestExitCodes pins for classify (docs/RESILIENCE.md)."""

    def _err(self, capsys):
        err = capsys.readouterr().err
        assert "Traceback" not in err
        return err

    def test_save_index_missing_train_exits_2(self, tmp_path, capsys):
        assert run(["save-index", "/no/such.arff", str(tmp_path / "x")]) == 2
        assert "error:" in self._err(capsys)

    def test_save_index_bad_k_exits_2(self, paths, tmp_path, capsys):
        assert run(["save-index", paths[0], str(tmp_path / "x"),
                    "--k", "0"]) == 2
        assert "k must be >= 1" in self._err(capsys)

    def test_save_index_k_over_n_exits_2(self, paths, tmp_path, capsys):
        assert run(["save-index", paths[0], str(tmp_path / "x"),
                    "--k", "999999"]) == 2
        assert "exceeds" in self._err(capsys)

    def test_save_index_unknown_backend_exits_2(self, paths, tmp_path,
                                                capsys):
        assert run(["save-index", paths[0], str(tmp_path / "x"),
                    "--backend", "no-such"]) == 2
        assert "unavailable" in self._err(capsys)

    def test_save_index_foreign_dir_exits_2(self, paths, tmp_path, capsys):
        victim = tmp_path / "home"
        victim.mkdir()
        (victim / "keep.txt").write_text("mine")
        assert run(["save-index", paths[0], str(victim)]) == 2
        assert "refusing" in self._err(capsys)
        assert (victim / "keep.txt").exists()

    def test_serve_missing_index_exits_2(self, capsys):
        assert run(["serve", "/no/such/index"]) == 2
        assert "not found" in self._err(capsys)

    def test_serve_non_artifact_exits_2(self, tmp_path, capsys):
        plain = tmp_path / "plain"
        plain.mkdir()
        (plain / "junk").write_text("x")
        assert run(["serve", str(plain)]) == 2
        assert "not an index artifact" in self._err(capsys)

    def test_serve_bad_policy_exits_2(self, capsys):
        for extra in (["--max-batch", "0"], ["--max-wait-ms", "-1"],
                      ["--deadline-ms", "0"], ["--port", "99999"],
                      ["--max-batch", "64", "--max-queue-rows", "8"],
                      ["--warmup-batches", "a,b"],
                      # The observability knobs keep the same contract.
                      ["--flight-recorder-size", "-1"],
                      ["--slowest-k", "-1"],
                      ["--slo-availability-target", "1.5"],
                      ["--slo-latency-target", "0"],
                      ["--slo-fast-rung-target", "-0.1"],
                      ["--slo-latency-ms", "0"],
                      ["--slo-windows", "5,x"],
                      ["--slo-windows", "0"],
                      # The quality/drift knobs (PR 7) keep it too.
                      ["--shadow-rate", "1.5"],
                      ["--shadow-rate", "-0.1"],
                      ["--drift-rate", "2"],
                      ["--quality-queue", "0"],
                      ["--slo-quality-target", "1"],
                      ["--slo-quality-target", "0"],
                      # The cost & capacity knobs (PR 8) keep it too.
                      ["--capacity-window-s", "0"],
                      ["--capacity-window-s", "4"],
                      # The mutable-tier knobs (PR 10) keep it too.
                      ["--delta-cap", "0"],
                      ["--compact-threshold", "0"],
                      ["--compact-interval-s", "-1"],
                      # The bucket-ladder / result-cache knobs (PR 12).
                      ["--batch-buckets", "a,b"],
                      ["--batch-buckets", "0"],
                      ["--batch-buckets", "16,512"],  # > --max-batch 256
                      ["--result-cache-rows", "-1"]):
            assert run(["serve", "/irrelevant/index", *extra]) == 2, extra
            assert "error:" in self._err(capsys)

    def test_serve_bad_cost_accounting_choice_exits_2(self, capsys):
        # argparse choice validation: anything but on/off is usage error.
        assert run(["serve", "/irrelevant/index",
                    "--cost-accounting", "maybe"]) == 2
        assert "Traceback" not in capsys.readouterr().err

    def test_serve_bad_mutable_choice_exits_2(self, capsys):
        assert run(["serve", "/irrelevant/index",
                    "--mutable", "maybe"]) == 2
        assert "Traceback" not in capsys.readouterr().err

    def test_serve_missing_positional_exits_2(self, capsys):
        assert run(["serve"]) == 2

    # -- PR 20: history / alerting flag contracts (all pre-boot) ---------

    def test_serve_bad_history_flags_exit_2(self, capsys):
        for extra in (["--history-interval-s", "0"],
                      ["--history-interval-s", "-1"],
                      # retention below the sampling interval is unusable
                      ["--history-dir", "/tmp/h",
                       "--history-interval-s", "10",
                       "--history-retention-s", "5"],
                      ["--history-retention-s", "0"]):
            assert run(["serve", "/irrelevant/index", *extra]) == 2, extra
            assert "error:" in self._err(capsys)

    def test_serve_bad_alert_rules_exit_2(self, tmp_path, capsys):
        assert run(["serve", "/irrelevant/index",
                    "--alert-rules", "/no/such/rules.json"]) == 2
        assert "error:" in self._err(capsys)
        bad = tmp_path / "rules.json"
        bad.write_text("{not json")
        assert run(["serve", "/irrelevant/index",
                    "--alert-rules", str(bad)]) == 2
        assert "error:" in self._err(capsys)
        # A capture action needs the workload recorder armed.
        bad.write_text(json.dumps([
            {"name": "x", "type": "threshold", "metric": "m", "value": 1,
             "actions": [{"do": "capture"}]}]))
        assert run(["serve", "/irrelevant/index",
                    "--alert-rules", str(bad)]) == 2
        assert "--capture-dir" in self._err(capsys)
        # A profile action writes under the history dir.
        bad.write_text(json.dumps([
            {"name": "x", "type": "threshold", "metric": "m", "value": 1,
             "actions": [{"do": "profile"}]}]))
        assert run(["serve", "/irrelevant/index",
                    "--alert-rules", str(bad)]) == 2
        assert "--history-dir" in self._err(capsys)

    def test_route_alert_rules_contracts_exit_2(self, tmp_path, capsys):
        rules = tmp_path / "rules.json"
        # Routers have no request SLOs: burn_rate rules are a serve thing.
        rules.write_text(json.dumps([
            {"name": "b", "type": "burn_rate", "threshold": 1.0}]))
        assert run(["route", "http://127.0.0.1:1",
                    "--alert-rules", str(rules)]) == 2
        assert "error:" in self._err(capsys)
        # ...and no workload recorder for capture actions.
        rules.write_text(json.dumps([
            {"name": "x", "type": "threshold", "metric": "m", "value": 1,
             "actions": [{"do": "capture"}]}]))
        assert run(["route", "http://127.0.0.1:1",
                    "--alert-rules", str(rules)]) == 2
        assert "workload recorder" in self._err(capsys)

    def test_route_bad_history_flags_exit_2(self, capsys):
        assert run(["route", "http://127.0.0.1:1",
                    "--history-interval-s", "0"]) == 2
        assert "error:" in self._err(capsys)

    def test_history_usage_errors_exit_2(self, tmp_path, capsys):
        assert run(["history", "/no/such/dir"]) == 2
        assert "error:" in self._err(capsys)
        empty = tmp_path / "h"
        empty.mkdir()
        assert run(["history", str(empty), "--window", "bogus"]) == 2
        assert "error:" in self._err(capsys)

    def test_report_usage_errors_exit_2(self, capsys):
        assert run(["report", "--history", "/no/such/dir"]) == 2
        assert "error:" in self._err(capsys)
        assert "Traceback" not in capsys.readouterr().err


class TestDumpPredictions:
    def test_dump_matches_oracle(self, paths, tmp_path):
        import numpy as np

        from knn_tpu.backends.oracle import knn_oracle
        from knn_tpu.data.arff import load_arff

        out = tmp_path / "preds.npy"
        assert run([paths[0], paths[1], "3", "--backend", "oracle",
                    "--dump-predictions", str(out)], stdout=io.StringIO()) == 0
        train, test = load_arff(paths[0]), load_arff(paths[1])
        want = knn_oracle(
            train.features, train.labels, test.features, 3, train.num_classes
        )
        np.testing.assert_array_equal(np.load(out), want)

    def test_sweep_dumps_one_file_per_k(self, paths, tmp_path):
        import numpy as np

        base = tmp_path / "p.npy"
        assert run([paths[0], paths[1], "1", "--sweep-k", "1,5",
                    "--engine", "xla", "--dump-predictions", str(base)],
                   stdout=io.StringIO()) == 0
        for k in (1, 5):
            single = tmp_path / f"single{k}.npy"
            assert run([paths[0], paths[1], str(k), "--backend", "oracle",
                        "--dump-predictions", str(single)],
                       stdout=io.StringIO()) == 0
            np.testing.assert_array_equal(
                np.load(tmp_path / f"p.k{k}.npy"), np.load(single)
            )

    def test_unwritable_dump_path_clean_error(self, paths, capsys):
        out = io.StringIO()
        assert run([paths[0], paths[1], "1", "--backend", "oracle",
                    "--dump-predictions", "/no/such/dir/p.npy"],
                   stdout=out) == 1
        assert "error:" in capsys.readouterr().err
        # The result line still printed — the compute is not discarded.
        assert LINE_RE.match(out.getvalue().strip())


class TestPlatformStability:
    def test_cli_entry_does_not_trample_explicit_platform_config(self, paths):
        """Regression (r5): with an ambient JAX_PLATFORMS (the axon tunnel
        exports 'axon'), a CLI entry running BEFORE the first backend
        initialization re-applied the environment over an explicitly-set
        jax_platforms config — flipping an 8-device CPU session to the
        1-chip TPU mid-process. init_from_env must only honor the
        framework's own KNN_TPU_PLATFORM knob."""
        import os
        import subprocess
        import sys
        import textwrap

        env = dict(
            os.environ,
            JAX_PLATFORMS="bogus_ambient_platform",
            XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                       + " --xla_force_host_platform_device_count=8").strip(),
        )
        env.pop("KNN_TPU_PLATFORM", None)
        code = textwrap.dedent(f"""
            import jax
            jax.config.update("jax_platforms", "cpu")  # explicit in-process
            import io
            from knn_tpu.cli import run
            run(["/nope/train.arff", "/nope/test.arff", "1"],
                stdout=io.StringIO())  # errors out AFTER init_from_env ran
            assert len(jax.devices()) == 8, jax.devices()
            print("DEVICES-OK")
        """)
        proc = subprocess.run(
            [sys.executable, "-c", code], env=env, capture_output=True,
            text=True, cwd=os.path.dirname(os.path.dirname(__file__)),
        )
        assert "DEVICES-OK" in proc.stdout, (proc.stdout, proc.stderr)
