"""Multi-device strategy tests on the 8-virtual-device CPU mesh (conftest.py).

Every distributed path must agree with the oracle on *predictions* — not just
accuracy (SURVEY.md §4) — including under ragged shapes and duplicate-row ties.
"""

import jax
import numpy as np
import pytest

from knn_tpu.backends.oracle import knn_oracle
from knn_tpu.parallel.mesh import make_mesh, make_mesh_2d, default_mesh_shape
from knn_tpu.parallel.query_sharded import predict_query_sharded
from knn_tpu.parallel.train_sharded import predict_train_sharded
from knn_tpu.parallel.ring import predict_ring
from tests import fixtures


@pytest.fixture(scope="module")
def problem(rng=None):
    rng = np.random.default_rng(7)
    n, q, d, c = 1210, 133, 6, 5
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)  # int grid → ties
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, 40, replace=False)],  # exact duplicates
         rng.integers(0, 4, (q - 40, d)).astype(np.float32)]
    )
    return train_x, train_y, test_x, c


def oracle_preds(problem, k):
    train_x, train_y, test_x, c = problem
    return knn_oracle(train_x, train_y, test_x, k, c)


class TestMesh:
    def test_eight_virtual_devices(self):
        assert len(jax.devices()) == 8

    def test_default_mesh_shape(self):
        assert default_mesh_shape(8) == (4, 2)
        assert default_mesh_shape(4) == (2, 2)
        assert default_mesh_shape(7) == (7, 1)
        assert default_mesh_shape(16) == (4, 4)

    def test_make_mesh_too_many(self):
        with pytest.raises(ValueError, match="devices"):
            make_mesh(99)


class TestQuerySharded:
    @pytest.mark.parametrize("k", [1, 5])
    def test_matches_oracle(self, problem, k):
        train_x, train_y, test_x, c = problem
        got = predict_query_sharded(
            train_x, train_y, test_x, k, c, query_tile=16, train_tile=256
        )
        np.testing.assert_array_equal(got, oracle_preds(problem, k))

    def test_subset_of_devices(self, problem):
        train_x, train_y, test_x, c = problem
        got = predict_query_sharded(
            train_x, train_y, test_x, 3, c, num_devices=4, query_tile=8, train_tile=128
        )
        np.testing.assert_array_equal(got, oracle_preds(problem, 3))

    def test_single_device_mesh(self, problem):
        train_x, train_y, test_x, c = problem
        got = predict_query_sharded(
            train_x, train_y, test_x, 5, c, num_devices=1, query_tile=32, train_tile=256
        )
        np.testing.assert_array_equal(got, oracle_preds(problem, 5))


class TestTrainSharded:
    @pytest.mark.parametrize("mesh_shape", [(1, 8), (2, 4), (4, 2), (8, 1)])
    def test_mesh_shapes_match_oracle(self, problem, mesh_shape):
        train_x, train_y, test_x, c = problem
        got = predict_train_sharded(
            train_x, train_y, test_x, 5, c,
            mesh_shape=mesh_shape, query_tile=16, train_tile=64,
        )
        np.testing.assert_array_equal(got, oracle_preds(problem, 5))

    def test_tie_stability_across_shards(self):
        # All train rows identical: predictions must come from the k lowest
        # *global* indices no matter the shard layout.
        train_x = np.ones((64, 3), np.float32)
        train_y = np.arange(64, dtype=np.int32) % 7
        test_x = np.ones((8, 3), np.float32)
        want = knn_oracle(train_x, train_y, test_x, 5, 7)
        got = predict_train_sharded(
            train_x, train_y, test_x, 5, 7, mesh_shape=(1, 8), query_tile=8,
            train_tile=8,
        )
        np.testing.assert_array_equal(got, want)

    def test_k_larger_than_shard(self, problem):
        # k=20 over 8 shards of ~151 rows — fine; also k > train_tile.
        train_x, train_y, test_x, c = problem
        got = predict_train_sharded(
            train_x, train_y, test_x, 20, c, mesh_shape=(1, 8), query_tile=16,
            train_tile=16,
        )
        np.testing.assert_array_equal(got, oracle_preds(problem, 20))


class TestRing:
    @pytest.mark.parametrize("k", [1, 5])
    def test_matches_oracle(self, problem, k):
        train_x, train_y, test_x, c = problem
        got = predict_ring(train_x, train_y, test_x, k, c)
        np.testing.assert_array_equal(got, oracle_preds(problem, k))

    def test_tie_stability_rotated_order(self):
        # The ring visits shards in rotated order per device; the
        # (dist, global-index) merge must still pick lowest indices.
        train_x = np.ones((40, 2), np.float32)
        train_y = (np.arange(40, dtype=np.int32) * 3) % 9
        test_x = np.ones((16, 2), np.float32)
        want = knn_oracle(train_x, train_y, test_x, 7, 9)
        got = predict_ring(train_x, train_y, test_x, 7, 9)
        np.testing.assert_array_equal(got, want)

    def test_k_exceeds_shard_rows(self):
        # 8 devices × 5 rows each; k=12 > shard size.
        rng = np.random.default_rng(3)
        train_x = rng.normal(size=(40, 4)).astype(np.float32)
        train_y = rng.integers(0, 3, 40).astype(np.int32)
        test_x = rng.normal(size=(24, 4)).astype(np.float32)
        want = knn_oracle(train_x, train_y, test_x, 12, 3)
        got = predict_ring(train_x, train_y, test_x, 12, 3)
        np.testing.assert_array_equal(got, want)


class TestStripeEngine:
    """VERDICT r1 #1: every distributed path can obtain per-shard candidates
    from the lane-striped Pallas kernel (interpret mode on the CPU mesh) and
    must stay prediction-exact vs the oracle."""

    @pytest.mark.parametrize("k", [1, 5])
    def test_query_sharded_stripe(self, problem, k):
        train_x, train_y, test_x, c = problem
        got = predict_query_sharded(
            train_x, train_y, test_x, k, c, engine="stripe"
        )
        np.testing.assert_array_equal(got, oracle_preds(problem, k))

    @pytest.mark.parametrize("mesh_shape", [(2, 4), (4, 2), (1, 8)])
    def test_train_sharded_stripe(self, problem, mesh_shape):
        train_x, train_y, test_x, c = problem
        got = predict_train_sharded(
            train_x, train_y, test_x, 5, c,
            mesh_shape=mesh_shape, engine="stripe",
        )
        np.testing.assert_array_equal(got, oracle_preds(problem, 5))

    def test_train_sharded_stripe_cross_shard_ties(self):
        # All train rows identical: the k lowest *global* indices must win
        # regardless of which shard (and stripe lane) they live in.
        train_x = np.ones((64, 3), np.float32)
        train_y = np.arange(64, dtype=np.int32) % 7
        test_x = np.ones((8, 3), np.float32)
        want = knn_oracle(train_x, train_y, test_x, 5, 7)
        got = predict_train_sharded(
            train_x, train_y, test_x, 5, 7, mesh_shape=(1, 8), engine="stripe"
        )
        np.testing.assert_array_equal(got, want)

    def test_ring_stripe(self, problem):
        train_x, train_y, test_x, c = problem
        got = predict_ring(train_x, train_y, test_x, 5, c, engine="stripe")
        np.testing.assert_array_equal(got, oracle_preds(problem, 5))

    def test_ring_tiled(self, problem):
        train_x, train_y, test_x, c = problem
        got = predict_ring(
            train_x, train_y, test_x, 5, c,
            engine="tiled", query_tile=16, train_tile=64,
        )
        np.testing.assert_array_equal(got, oracle_preds(problem, 5))


class TestRingXl:
    def test_ring_tiled_xl_without_full_matrix(self):
        # VERDICT r1 #3: an xl-shaped problem — >=1M padded train rows over 8
        # devices — must pass through the ring without materializing the
        # per-shard [q_local, N/P] distance matrix (tiled engine: per-step
        # memory is O(query_tile x train_tile)).
        rng = np.random.default_rng(11)
        n, q, d, c, k = 1_050_000, 48, 4, 6, 5
        train_x = rng.integers(0, 64, (n, d)).astype(np.float32)
        train_y = rng.integers(0, c, n).astype(np.int32)
        test_x = np.concatenate(
            [train_x[rng.choice(n, q // 2, replace=False)],
             rng.integers(0, 64, (q - q // 2, d)).astype(np.float32)]
        )
        want = knn_oracle(train_x, train_y, test_x, k, c)
        got = predict_ring(
            train_x, train_y, test_x, k, c,
            engine="tiled", query_tile=8, train_tile=4096,
        )
        np.testing.assert_array_equal(got, want)


class TestFixtureParity:
    """Small reference fixture through every distributed path."""

    @pytest.mark.parametrize("path", ["query", "train", "ring"])
    def test_small_k5(self, small, path):
        train, test = small
        want = knn_oracle(
            train.features, train.labels, test.features, 5, train.num_classes
        )
        if path == "query":
            got = predict_query_sharded(
                train.features, train.labels, test.features, 5, train.num_classes,
                query_tile=8, train_tile=128,
            )
        elif path == "train":
            got = predict_train_sharded(
                train.features, train.labels, test.features, 5, train.num_classes,
                mesh_shape=(2, 4), query_tile=8, train_tile=64,
            )
        else:
            got = predict_ring(
                train.features, train.labels, test.features, 5, train.num_classes
            )
        np.testing.assert_array_equal(got, want)
