"""Chaos suite: the resilience subsystem under deterministic fault injection.

The acceptance contract (ISSUE 2): every (fault point, mode) combination
ends in recovery with bit-identical predictions, or in a typed error —
never an unhandled traceback. Runs entirely on CPU; the injection harness
(knn_tpu/resilience/faults.py) stands in for the hardware failures.
"""

import io

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.resilience import degrade, faults, retry
from knn_tpu.resilience.errors import (
    CollectiveError,
    CompileError,
    DataError,
    DeviceError,
    ResilienceError,
    WorkerLostError,
    classify_exception,
)
from tests import fixtures


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    # Chaos at full speed: no backoff sleeps in tests.
    monkeypatch.setenv("KNN_TPU_RETRY_BASE_MS", "0")


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    yield
    faults.install(None)  # never leak an armed plan into another test


@pytest.fixture(scope="module")
def golden(request):
    """Oracle predictions for (small, k=3) — the bit-identical target every
    recovered/degraded run must reproduce."""
    from knn_tpu.backends.oracle import knn_oracle

    train, test = fixtures.load_pair("small")
    preds = knn_oracle(
        train.features, train.labels, test.features, 3, train.num_classes
    )
    return train, test, preds


class TestErrors:
    def test_taxonomy_shape(self):
        assert issubclass(DataError, ValueError)
        assert issubclass(WorkerLostError, CollectiveError)
        for cls in (CompileError, DeviceError, CollectiveError):
            assert issubclass(cls, ResilienceError)
            assert not issubclass(cls, ValueError)

    def test_transient_defaults(self):
        assert not DataError("x").transient
        assert CompileError("x").transient
        assert CollectiveError("x").transient
        assert DeviceError("x").transient
        assert not DeviceError("x", oom=True).transient

    def test_classify_oom(self):
        e = classify_exception(
            RuntimeError("RESOURCE_EXHAUSTED: Out of memory allocating"),
            "device.put",
        )
        assert isinstance(e, DeviceError) and e.oom and not e.transient

    def test_classify_by_site(self):
        assert isinstance(
            classify_exception(RuntimeError("x"), "backend.compile"),
            CompileError,
        )
        assert isinstance(
            classify_exception(RuntimeError("x"), "collective.step"),
            CollectiveError,
        )
        w = classify_exception(ConnectionError("refused"), "multihost.init")
        assert isinstance(w, WorkerLostError) and w.reason == "ConnectionError"
        assert isinstance(
            classify_exception(OSError("io"), "device.put"), DeviceError
        )
        # Already-typed errors pass through unchanged.
        d = DataError("x")
        assert classify_exception(d, "device.put") is d


class TestFaultPlan:
    def test_modes(self):
        plan = faults.FaultPlan("device.put=2, backend.compile=always")
        assert plan.check("device.put") is not None
        assert plan.check("device.put") is not None
        assert plan.check("device.put") is None
        for _ in range(5):
            assert plan.check("backend.compile") is not None
        assert plan.check("collective.step") is None  # unarmed point

    def test_kind_override(self):
        kind, err = faults.FaultPlan("device.put=once:oom").check("device.put")
        assert kind == "oom" and isinstance(err, DeviceError) and err.oom
        kind, err = faults.FaultPlan("native.load=once").check("native.load")
        assert kind == "io" and isinstance(err, OSError)

    def test_probabilistic_is_seed_deterministic(self):
        def seq(seed):
            plan = faults.FaultPlan("device.put=p0.5", seed=seed)
            return [plan.check("device.put") is not None for _ in range(32)]

        assert seq(7) == seq(7)
        assert seq(7) != seq(8)  # astronomically unlikely to collide
        assert any(seq(7)) and not all(seq(7))

    def test_unknown_point_or_mode_rejected(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            faults.FaultPlan("no.such.point=once")
        with pytest.raises(ValueError, match="bad fault mode"):
            faults.FaultPlan("device.put=sometimes")
        with pytest.raises(ValueError, match="unknown fault kind"):
            faults.FaultPlan("device.put=once:nope")

    def test_inject_scopes_and_counts(self):
        with faults.inject("device.put=once") as plan:
            with pytest.raises(DeviceError):
                faults.fault_point("device.put")
            faults.fault_point("device.put")  # second activation passes
        assert plan.stats()["device.put"] == {"fired": 1, "activations": 2}
        faults.fault_point("device.put")  # disarmed again

    def test_env_install(self, monkeypatch):
        monkeypatch.setenv(faults.FAULT_ENV, "backend.compile=once")
        plan = faults.install_from_env()
        try:
            assert plan is not None
            with pytest.raises(CompileError):
                faults.fault_point("backend.compile")
        finally:
            monkeypatch.delenv(faults.FAULT_ENV)
            assert faults.install_from_env() is None

    def test_fault_point_typo_raises_under_armed_plan(self):
        with faults.inject("device.put=always"):
            with pytest.raises(ValueError, match="not a registered point"):
                faults.fault_point("device.putt")


class TestRetry:
    def test_backoff_schedule(self):
        assert retry.backoff_schedule(4, 25.0, 2000.0) == [25.0, 50.0, 100.0]
        assert retry.backoff_schedule(5, 1000.0, 2000.0) == [
            1000.0, 2000.0, 2000.0, 2000.0,
        ]

    def test_fail_once_recovers(self):
        calls = []
        with faults.inject("device.put=once"):
            out = retry.guarded_call(
                "device.put", lambda: calls.append(1) or 42
            )
        assert out == 42 and len(calls) == 1

    def test_fail_always_raises_typed(self):
        with faults.inject("device.put=always") as plan:
            with pytest.raises(DeviceError):
                retry.guarded_call("device.put", lambda: 42, attempts=3)
        assert plan.stats()["device.put"]["fired"] == 3  # all attempts tried

    def test_non_transient_not_retried(self):
        with faults.inject("device.put=always:oom") as plan:
            with pytest.raises(DeviceError) as ei:
                retry.guarded_call("device.put", lambda: 42, attempts=3)
        assert ei.value.oom
        assert plan.stats()["device.put"]["activations"] == 1  # no retry

    def test_raw_exception_classified_with_cause(self):
        boom = RuntimeError("kaboom")

        def fn():
            raise boom

        with pytest.raises(CompileError) as ei:
            retry.guarded_call("backend.compile", fn, attempts=1)
        assert ei.value.__cause__ is boom

    def test_deadline_stops_retrying(self):
        calls = []

        def fn():
            calls.append(1)
            raise OSError("transient")

        with pytest.raises(DeviceError):
            retry.guarded_call(
                "device.put", fn, attempts=10, base_ms=50.0, deadline_ms=1.0,
            )
        assert len(calls) == 1  # first backoff would blow the deadline

    def test_retry_counter_emitted(self):
        obs.enable()
        obs.reset()
        try:
            with faults.inject("device.put=once"):
                retry.guarded_call("device.put", lambda: 1)
            counters = obs.registry().to_json()
            assert counters["knn_retry_total"][0]["value"] >= 1
            assert "knn_fault_injected_total" in counters
        finally:
            obs.disable()
            obs.reset()


class TestRetryJitter:
    """KNN_TPU_RETRY_JITTER (default OFF): seeded backoff jitter that
    de-synchronizes concurrent handler threads without breaking chaos
    replay — bounds and replay determinism pinned here."""

    def _capture_sleeps(self, monkeypatch):
        seen = []
        monkeypatch.setattr(retry.time, "sleep", lambda s: seen.append(s))
        return seen

    def _failing(self):
        raise DeviceError("transient blip", transient=True)

    def test_default_off_sleeps_schedule_verbatim(self, monkeypatch):
        monkeypatch.delenv("KNN_TPU_RETRY_JITTER", raising=False)
        seen = self._capture_sleeps(monkeypatch)
        with pytest.raises(DeviceError):
            retry.guarded_call("device.put", self._failing, attempts=3,
                               base_ms=8.0, max_ms=1000.0)
        assert seen == [0.008, 0.016]  # the deterministic schedule, exactly

    def test_jitter_bounded_below_half_above_schedule(self, monkeypatch):
        monkeypatch.setenv("KNN_TPU_RETRY_JITTER", "1")
        retry.reset_jitter(7)
        seen = self._capture_sleeps(monkeypatch)
        with pytest.raises(DeviceError):
            retry.guarded_call("device.put", self._failing, attempts=6,
                               base_ms=8.0, max_ms=1000.0)
        schedule = [s / 1e3 for s in retry.backoff_schedule(6, 8.0, 1000.0)]
        assert len(seen) == len(schedule)
        for got, base in zip(seen, schedule):
            assert base / 2 <= got <= base, (got, base)
        assert seen != schedule  # jitter actually moved something

    def test_jitter_replay_deterministic_from_seed(self, monkeypatch):
        monkeypatch.setenv("KNN_TPU_RETRY_JITTER", "1")
        runs = []
        for _ in range(2):
            retry.reset_jitter(123)
            seen = []
            monkeypatch.setattr(retry.time, "sleep",
                                lambda s: seen.append(s))
            with pytest.raises(DeviceError):
                retry.guarded_call("device.put", self._failing, attempts=4,
                                   base_ms=16.0, max_ms=1000.0)
            runs.append(seen)
        assert runs[0] == runs[1]  # same seed -> identical sleep sequence

    def test_apply_jitter_bounds_over_many_draws(self):
        retry.reset_jitter(99)
        draws = [retry.apply_jitter(100.0) for _ in range(500)]
        assert all(50.0 <= d <= 100.0 for d in draws)
        assert max(draws) - min(draws) > 10.0  # it actually spreads

    def test_seed_env_feeds_jitter(self, monkeypatch):
        monkeypatch.setenv("KNN_TPU_RETRY_JITTER", "1")
        monkeypatch.setenv(faults.SEED_ENV, "31337")
        retry.reset_jitter()  # re-reads KNN_TPU_FAULT_SEED
        a = [retry.apply_jitter(100.0) for _ in range(3)]
        retry.reset_jitter()
        b = [retry.apply_jitter(100.0) for _ in range(3)]
        assert a == b


def _ladder_predict(backend, train, test, k=3, opts=None, **kw):
    return degrade.predict_with_ladder(backend, train, test, k, opts, **kw)


class TestLadder:
    def test_clean_run_is_not_degraded(self, golden):
        train, test, want = golden
        res = _ladder_predict("tpu", train, test)
        assert not res.degraded and res.backend == "tpu"
        np.testing.assert_array_equal(res.predictions, want)

    def test_device_fail_always_degrades_to_host_rung(self, golden, capsys):
        train, test, want = golden
        with faults.inject("device.put=always"):
            res = _ladder_predict("tpu", train, test)
        assert res.degraded and res.backend in ("native", "oracle")
        np.testing.assert_array_equal(res.predictions, want)
        assert "falling back" in capsys.readouterr().err

    def test_no_fallback_raises_typed(self, golden):
        train, test, _ = golden
        with faults.inject("device.put=always"):
            with pytest.raises(DeviceError):
                _ladder_predict("tpu", train, test, no_fallback=True)

    def test_oom_halves_query_batch_then_succeeds(self, golden, capsys):
        train, test, want = golden
        # Two OOMs, then clean: the ladder should stay on the tpu rung and
        # serve from a quartered batch.
        with faults.inject("device.put=2:oom"):
            res = _ladder_predict("tpu", train, test)
        assert res.backend == "tpu"
        assert res.opts["query_batch"] == test.num_instances // 4
        np.testing.assert_array_equal(res.predictions, want)
        assert "query_batch" in capsys.readouterr().err

    def test_oom_always_exhausts_batches_then_degrades(self, golden, capsys):
        train, test, want = golden
        with faults.inject("device.put=always:oom"):
            res = _ladder_predict("tpu", train, test)
        assert res.degraded and res.backend in ("native", "oracle")
        np.testing.assert_array_equal(res.predictions, want)

    def test_sharded_degrades_to_single_device(self, golden, capsys):
        train, test, want = golden
        with faults.inject("collective.step=always"):
            res = _ladder_predict("tpu-sharded", train, test)
        assert res.degraded and res.backend == "tpu"
        np.testing.assert_array_equal(res.predictions, want)

    def test_fallback_counter_emitted(self, golden):
        train, test, _ = golden
        obs.enable()
        obs.reset()
        try:
            with faults.inject("backend.compile=always"):
                _ladder_predict("tpu", train, test)
            recs = obs.registry().to_json()["knn_fallback_total"]
            moves = {
                (r["labels"]["from_backend"], r["labels"]["to"]) for r in recs
            }
            assert ("tpu", "tpu-pallas") in moves
        finally:
            obs.disable()
            obs.reset()

    def test_unavailable_backend_static_fallback(self):
        assert degrade.fallback_for("native", {"oracle", "tpu"}) == "oracle"
        assert degrade.fallback_for("tpu-sharded", {"tpu", "oracle"}) == "tpu"
        assert degrade.fallback_for("oracle", {"tpu"}) is None
        assert degrade.known_backend("tpu-ring")
        assert not degrade.known_backend("no-such")

    def test_opts_sanitized_for_fallback_rungs(self):
        opts = {"num_devices": 4, "engine": "full", "precision": "exact",
                "approx": True}
        out = degrade.opts_for_rung("tpu", "tpu-ring", opts)
        assert "num_devices" not in out
        assert out["engine"] == "auto"
        assert out["approx"] is True and out["precision"] == "exact"
        # The origin rung keeps everything verbatim.
        assert degrade.opts_for_rung("tpu-ring", "tpu-ring", opts) == opts


MATRIX_BACKEND = {
    # fault point -> backend whose path activates it
    "arff.parse": "tpu",
    "device.put": "tpu",
    "backend.compile": "tpu",
    "collective.step": "tpu-sharded",
    "native.load": "native",
}


class TestFaultMatrix:
    """Every fault point x {fail-once, fail-always}: recovery with
    bit-identical predictions or a typed error — never a raw traceback.
    (multihost.init runs in TestMultihost; its recovery is solo mode.)"""

    @pytest.mark.parametrize("point", sorted(MATRIX_BACKEND))
    def test_fail_once_recovers_bit_identical(self, point, golden):
        train, test, want = golden
        backend = MATRIX_BACKEND[point]
        if backend == "native":
            pytest.importorskip("knn_tpu.backends.native")
        # Recovery mode: an IO-flavored blip for the parse point (a
        # deterministic DataError is *correctly* not retried), the point's
        # natural transient kind elsewhere.
        spec = f"{point}=once:io" if point == "arff.parse" else f"{point}=once"
        datasets = fixtures.datasets_dir()
        with faults.inject(spec) as plan:
            if point == "arff.parse":
                from knn_tpu.data.arff import load_arff

                ds = load_arff(str(datasets / "small-train.arff"))
                assert ds.num_instances == train.num_instances
            else:
                res = _ladder_predict(backend, train, test)
                assert not res.degraded, (
                    f"fail-once at {point} should be absorbed by retry, "
                    f"not the ladder"
                )
                np.testing.assert_array_equal(res.predictions, want)
        assert plan.stats()[point]["fired"] == 1

    @pytest.mark.parametrize("point", sorted(MATRIX_BACKEND))
    def test_fail_always_degrades_or_types(self, point, golden):
        train, test, want = golden
        backend = MATRIX_BACKEND[point]
        if backend == "native":
            pytest.importorskip("knn_tpu.backends.native")
        with faults.inject(f"{point}=always"):
            if point == "arff.parse":
                from knn_tpu.data.arff import load_arff

                with pytest.raises(DataError):
                    load_arff(str(fixtures.datasets_dir() / "small-train.arff"))
            else:
                res = _ladder_predict(backend, train, test)
                assert res.degraded
                assert res.backend != backend
                np.testing.assert_array_equal(res.predictions, want)

    def test_native_parse_degrades_to_python_parser(self, golden):
        # The ingest mini-ladder: native parser lost -> pure-Python twin,
        # identical arrays.
        train, _, _ = golden
        from knn_tpu.data.arff import load_arff

        path = str(fixtures.datasets_dir() / "small-train.arff")
        with faults.inject("native.load=always"):
            ds = load_arff(path)
        np.testing.assert_array_equal(ds.features, train.features)
        np.testing.assert_array_equal(ds.labels, train.labels)


class TestMultihost:
    def test_init_failure_degrades_to_solo(self, capsys, monkeypatch):
        # The satellite contract: no bare `except Exception` swallow — the
        # lost worker is logged, typed, counted, and the run degrades solo.
        for var in ("KNN_TPU_COORD_ADDR", "KNN_TPU_NUM_PROCS",
                    "KNN_TPU_PROC_ID"):
            monkeypatch.delenv(var, raising=False)
        from knn_tpu.parallel.multihost import _worker_main

        d = fixtures.datasets_dir()
        obs.enable()
        obs.reset()
        try:
            with faults.inject("multihost.init=always"):
                rc = _worker_main([
                    str(d / "small-train.arff"), str(d / "small-test.arff"),
                    "3",
                ])
            assert rc == 0
            err = capsys.readouterr().err
            assert "WorkerLostError" in err and "single-process" in err
            recs = obs.registry().to_json()
            assert recs["knn_worker_lost_total"][0]["value"] == 1
            assert recs["knn_worker_lost_total"][0]["labels"]["reason"] \
                == "injected"
        finally:
            obs.disable()
            obs.reset()


class TestCliChaos:
    def test_cli_recovers_from_transient_fault(self, monkeypatch, capsys):
        from knn_tpu.cli import run

        d = fixtures.datasets_dir()
        monkeypatch.setenv("KNN_TPU_FAULTS", "device.put=once")
        out = io.StringIO()
        try:
            rc = run([str(d / "small-train.arff"), str(d / "small-test.arff"),
                      "3", "--backend", "tpu"], stdout=out)
        finally:
            monkeypatch.delenv("KNN_TPU_FAULTS")
            faults.install_from_env()
        assert rc == 0
        assert "required" in out.getvalue()  # the canonical result line

    def test_cli_degrades_and_still_answers(self, monkeypatch, capsys):
        from knn_tpu.cli import run

        d = fixtures.datasets_dir()
        monkeypatch.setenv("KNN_TPU_FAULTS", "backend.compile=always")
        out = io.StringIO()
        try:
            rc = run([str(d / "small-train.arff"), str(d / "small-test.arff"),
                      "3", "--backend", "tpu"], stdout=out)
        finally:
            monkeypatch.delenv("KNN_TPU_FAULTS")
            faults.install_from_env()
        assert rc == 0
        assert "falling back" in capsys.readouterr().err
        assert "required" in out.getvalue()
