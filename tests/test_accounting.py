"""Cost & capacity observability contracts (docs/OBSERVABILITY.md §Cost &
capacity).

The load-bearing claim is **attribution conservation**: the per-request
device-ms shares of every dispatch sum to the measured dispatch wall —
summed over any mix of classes, rungs, coalesced batches, and concurrent
clients, ``knn_cost_device_ms_total`` equals
``knn_cost_dispatch_wall_ms_total`` to float precision. Plus: shares are
proportional to rows, a deadline-expired-mid-fallback request is
attributed only the attempts it rode, class labels survive the 4xx/5xx
paths, padded (compiled-shape) rows are measured wherever the engine pads,
and the capacity math (duty cycle / occupancy / rates / Little's law /
headroom) is pinned against a fake clock like ``slo.py``'s tests.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.obs import accounting as acct
from knn_tpu.obs.accounting import (
    CostAccountant,
    dispatch_padded_rows,
    padded_query_rows,
    valid_request_class,
)
from knn_tpu.obs.capacity import CapacityTracker
from knn_tpu.obs.slo import SecondRing
from knn_tpu.resilience.errors import (
    DeadlineExceededError,
    DeviceError,
    OverloadError,
)
from knn_tpu.serve.batcher import MicroBatcher


def _problem(rng, n=300, q=40, d=5, c=5):
    train_x = rng.normal(size=(n, d)).astype(np.float32)
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = rng.normal(size=(q, d)).astype(np.float32)
    return (Dataset(train_x, train_y),
            Dataset(test_x, np.zeros(q, np.int32)))


@pytest.fixture
def obs_on():
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


def _counter_sum(registry, name):
    return sum(i.value for i in registry.instruments() if i.name == name)


def _assert_conservation(registry):
    dev = _counter_sum(registry, "knn_cost_device_ms_total")
    wall = _counter_sum(registry, "knn_cost_dispatch_wall_ms_total")
    assert wall > 0
    assert dev == pytest.approx(wall, rel=1e-9)


# ---------------------------------------------------------------------------
# Request-class validation + padded-rows math


class TestRequestClass:
    def test_valid(self):
        for cls in ("interactive", "bulk", "a", "x" * 32, "t-1_2.3"):
            assert valid_request_class(cls), cls

    def test_invalid(self):
        for cls in ("", "x" * 33, "UPPER", "has space", "emoji☃",
                    'quo"te', "new\nline"):
            assert not valid_request_class(cls), cls


class TestPaddedRows:
    def test_xla_pads_to_128(self):
        assert padded_query_rows("xla", 1) == 128
        assert padded_query_rows("xla", 128) == 128
        assert padded_query_rows("xla", 129) == 256
        assert padded_query_rows("xla", 0) == 0

    def test_host_engines_pad_nothing(self):
        assert padded_query_rows("oracle", 7) == 7

    def test_stripe_quantizes_to_block_q(self):
        from knn_tpu.ops.pallas_knn import stripe_block_sizes

        bq, _ = stripe_block_sizes(None, None, 5, 3, d_pad=8)
        pad = padded_query_rows("stripe", 5, num_features=5, k=3)
        assert pad == -(-5 // bq) * bq
        assert pad >= 5

    def test_dispatch_chunking_sums_per_chunk(self, rng):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        # 10 rows at cap 4 -> chunks of 4, 4, 2 -> 3 x 128 padded.
        assert dispatch_padded_rows(model, "xla", 10, 4) == 3 * 128
        assert dispatch_padded_rows(model, "oracle", 10, 4) == 10

    def test_record_serve_batch_padded_histogram(self, obs_on):
        from knn_tpu.obs import instrument

        instrument.record_serve_batch(2, 5, 1.0, padded_rows=128)
        names = {i.name for i in obs_on.instruments()}
        assert "knn_serve_batch_padded_rows" in names
        assert "knn_serve_batch_rows" in names

    def test_engine_span_carries_padded_rows(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        model.kneighbors(test)
        spans = [s for s in obs.tracer().spans() if s.name == "distance"]
        assert spans, "no distance span recorded"
        assert spans[-1].attrs["rows"] == test.num_instances
        assert spans[-1].attrs["padded_rows"] == \
            -(-test.num_instances // 128) * 128


# ---------------------------------------------------------------------------
# Attribution invariants through the batcher


class TestAttribution:
    def test_proportional_shares_in_one_coalesced_batch(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        model.predict(test)  # warm
        obs.reset()
        accountant = CostAccountant()
        # max_batch 4 closes the batch exactly when 1+3 rows are queued;
        # the huge wait window makes the coalescing deterministic.
        with MicroBatcher(model, max_batch=4, max_wait_ms=5000.0,
                          accounting=accountant) as b:
            ha = b.submit(test.features[:1], "predict",
                          request_class="interactive")
            hb = b.submit(test.features[1:4], "kneighbors",
                          request_class="bulk")
            ha.result(timeout=60)
            hb.result(timeout=60)
        ca, cb = ha.meta["cost"], hb.meta["cost"]
        assert ca["class"] == "interactive" and cb["class"] == "bulk"
        assert ca["rows"] == 1 and cb["rows"] == 3
        # Proportional to rows: the 3-row request paid 3x the 1-row one.
        assert cb["device_ms"] == pytest.approx(3 * ca["device_ms"],
                                                rel=1e-6)
        assert cb["bytes"] >= ca["bytes"]
        _assert_conservation(obs_on)
        # The padded-rows waste counter measured the 128-row XLA quantum.
        assert _counter_sum(obs_on, "knn_cost_padded_rows_total") == 128 - 4

    def test_conservation_under_concurrent_mixed_load(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        model.predict(test)
        obs.reset()
        accountant = CostAccountant()
        costs, lock = [], threading.Lock()

        def client(cid):
            mine = []
            for i in range(10):
                kind = "predict" if (cid + i) % 2 == 0 else "kneighbors"
                lo = (cid * 10 + i) % (test.num_instances - 3)
                h = batcher.submit(test.features[lo:lo + 1 + (i % 3)], kind,
                                   request_class=("bulk" if i % 3 == 0
                                                  else None))
                h.result(timeout=60)
                mine.append(h.meta["cost"])
            with lock:
                costs.extend(mine)

        with MicroBatcher(model, max_batch=8, max_wait_ms=1.0,
                          accounting=accountant) as batcher:
            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        assert len(costs) == 60
        assert all(c["device_ms"] > 0 for c in costs)
        _assert_conservation(obs_on)
        # The per-request blocks conserve too: their sum is the same total.
        total = sum(c["device_ms"] for c in costs)
        wall = _counter_sum(obs_on, "knn_cost_dispatch_wall_ms_total")
        assert total == pytest.approx(wall, rel=1e-9)
        exp = accountant.export()
        assert exp["totals"]["attributed_ms"] == pytest.approx(
            exp["totals"]["dispatch_wall_ms"], rel=1e-9)
        assert set(exp["classes"]) == {"interactive", "bulk"}

    def test_expired_mid_fallback_attributed_only_ridden_attempts(
            self, rng, obs_on, monkeypatch):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)

        def slow_boom(ds):
            time.sleep(0.4)
            raise DeviceError("slowly dying device")

        accountant = CostAccountant()
        b = MicroBatcher(model, max_batch=64, max_wait_ms=50.0,
                         accounting=accountant)
        try:
            monkeypatch.setattr(model, "kneighbors", slow_boom)
            ha = b.submit(test.features[0], deadline_ms=200,
                          request_class="interactive")
            hb = b.submit(test.features[1], request_class="bulk")
            with pytest.raises(DeadlineExceededError):
                ha.result(timeout=60)
            hb.result(timeout=60)
        finally:
            monkeypatch.undo()
            b.close()
        expired, survivor = ha.meta["cost"], hb.meta["cost"]
        # The expired request rode ONLY the failed fast attempt.
        assert set(expired["rungs"]) == {"fast"}
        # The survivor paid for the failed fast attempt AND the oracle
        # rung that answered it.
        assert set(survivor["rungs"]) == {"fast", "oracle"}
        # The fast attempt was split across both while both were live.
        assert expired["rungs"]["fast"] == pytest.approx(
            survivor["rungs"]["fast"], rel=1e-3)
        _assert_conservation(obs_on)
        exp = accountant.export()
        assert exp["classes"]["interactive"]["outcomes"] == {"expired": 1}
        assert exp["classes"]["bulk"]["outcomes"] == {"ok": 1}
        # rows are counted on the ANSWERING attempt only: the expired
        # request's row was never served.
        assert exp["classes"]["interactive"]["rows"] == 0
        assert exp["classes"]["bulk"]["rows"] == 1

    def test_class_survives_rejection_429_path(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        accountant = CostAccountant()
        with MicroBatcher(model, max_batch=2, max_queue_rows=2,
                          max_wait_ms=2000.0,
                          accounting=accountant) as batcher:
            parked = batcher.submit(test.features[:1], "predict",
                                    request_class="bulk")
            with pytest.raises(OverloadError):
                batcher.submit(test.features[1:3], "predict",
                               request_class="bulk")
            parked.result(timeout=30)
        exp = accountant.export()
        assert exp["classes"]["bulk"]["outcomes"]["rejected"] == 1
        assert exp["classes"]["bulk"]["outcomes"]["ok"] == 1
        rejected = [
            i.value for i in obs_on.instruments()
            if i.name == "knn_cost_requests_total"
            and dict(i.labels).get("class") == "bulk"
            and dict(i.labels).get("outcome") == "rejected"
        ]
        assert rejected == [1]

    def test_embedded_submit_rejects_invalid_class(self, rng, obs_on):
        # The HTTP front door 400s bad classes before submit; embedded
        # callers must hit the same wall — class strings become
        # Prometheus label values, so an unvalidated one could corrupt
        # the exposition text or explode cardinality.
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        with MicroBatcher(model, max_batch=2,
                          accounting=CostAccountant()) as batcher:
            for bad in ("UPPER", "new\nline", "x" * 33, "sp ace"):
                with pytest.raises(ValueError, match="request_class"):
                    batcher.submit(test.features[:1], "predict",
                                   request_class=bad)
            # Without an accountant, the tag is inert and unvalidated.
        with MicroBatcher(model, max_batch=2) as untagged:
            untagged.submit(test.features[:1], "predict",
                            request_class="UPPER").result(timeout=30)

    def test_class_cardinality_capped_at_overflow(self, obs_on):
        # Classes mint Prometheus series and per-class table slots, so a
        # client inventing a fresh class per request must hit a ceiling:
        # past MAX_CLASSES distinct values, admit_class folds into the
        # overflow class. Known classes keep resolving to themselves.
        from knn_tpu.obs import accounting as acct

        a = CostAccountant()
        admitted = {a.admit_class(f"c{i}") for i in range(200)}
        assert acct.OVERFLOW_CLASS in admitted
        distinct = admitted - {acct.OVERFLOW_CLASS}
        # interactive + other are pre-reserved, the rest first-come.
        assert len(distinct) == acct.MAX_CLASSES - 2
        for cls in distinct:
            assert a.admit_class(cls) == cls  # known stays itself
        assert a.admit_class("one-too-many") == acct.OVERFLOW_CLASS
        assert a.admit_class(acct.DEFAULT_CLASS) == acct.DEFAULT_CLASS

    def test_class_survives_queue_expiry_504_path(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        accountant = CostAccountant()
        with MicroBatcher(model, max_batch=64, max_wait_ms=2000.0,
                          accounting=accountant) as b:
            h = b.submit(test.features[:1], "predict", deadline_ms=20,
                         request_class="bulk")
            with pytest.raises(DeadlineExceededError):
                h.result(timeout=30)
        exp = accountant.export()
        assert exp["classes"]["bulk"]["outcomes"] == {"expired": 1}
        # Never dispatched -> no cost block, no attributed device time.
        assert "cost" not in h.meta
        assert exp["totals"]["dispatch_wall_ms"] == 0.0

    def test_default_class_is_interactive(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        accountant = CostAccountant()
        with MicroBatcher(model, max_batch=4, max_wait_ms=0.0,
                          accounting=accountant) as b:
            h = b.submit(test.features[:1], "predict")
            h.result(timeout=60)
        assert h.meta["cost"]["class"] == "interactive"

    def test_no_accounting_means_no_cost_instruments(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        with MicroBatcher(model, max_batch=4, max_wait_ms=0.0) as b:
            h = b.submit(test.features[:1], "predict",
                         request_class="bulk")
            h.result(timeout=60)
        assert "cost" not in h.meta
        leaked = [i.name for i in obs_on.instruments()
                  if i.name.startswith(("knn_cost_", "knn_capacity_"))]
        assert leaked == []


# ---------------------------------------------------------------------------
# Capacity math, pinned against a fake clock (the slo.py test recipe)


@pytest.fixture
def fake_clock(monkeypatch):
    import knn_tpu.obs.capacity as cap_mod
    import knn_tpu.obs.slo as slo_mod

    clock = [10_000.0]
    monkeypatch.setattr(slo_mod.time, "monotonic", lambda: clock[0])
    assert cap_mod.time is slo_mod.time  # both modules share stdlib time
    return clock


class TestCapacityMath:
    def test_duty_cycle_and_rates(self, fake_clock, obs_on):
        t = CapacityTracker(8, window_s=60)
        fake_clock[0] += 10.0  # 10 s of uptime, window still 60
        for _ in range(5):
            t.note_arrival(2)
        for _ in range(4):
            t.note_served(2, 50.0)
        # 4 dispatches x 500 ms busy over 10 s of wall -> duty 0.2.
        for _ in range(4):
            t.note_dispatch(500.0, 2, 128, 8)
        out = t.export()
        assert out["duty_cycle"] == pytest.approx(0.2)
        assert out["arrival_qps"] == pytest.approx(0.5)
        assert out["arrival_rows_per_s"] == pytest.approx(1.0)
        assert out["served_qps"] == pytest.approx(0.4)
        # Occupancy is rows over the COMPILED shape the dispatch padded
        # to (the bucket-ladder definition): 2 rows in a 128-row shape.
        assert out["occupancy_mean"] == pytest.approx(2 / 128, abs=1e-4)
        assert out["padded_row_waste_ratio"] == pytest.approx(
            (4 * 128 - 8) / (4 * 128), abs=1e-4)
        assert out["dispatch_rows_per_s"] == pytest.approx(8 / 2.0)
        assert out["mean_request_ms"] == pytest.approx(50.0)
        # Little's law over the ADMITTED rate (a rejected request never
        # enters the system): 0.4 served req/s x 0.05 s = 0.02 in flight —
        # NOT the 0.5 offered rate, which would inflate the estimate
        # exactly when the replica sheds.
        assert out["littles_law_concurrency"] == pytest.approx(0.02)

    def test_seed_two_point_headroom_model(self, fake_clock, obs_on):
        t = CapacityTracker(8, window_s=60)
        fake_clock[0] += 5.0
        # w(r) = 1 + 1*r exactly: w(1)=2, w(8)=9.
        t.seed_dispatch_model(1, 2.0)
        t.seed_dispatch_model(8, 9.0)
        out = t.export()
        m = out["dispatch_model"]
        assert m["source"] == "seed"
        assert m["a_ms"] == pytest.approx(1.0)
        assert m["b_ms_per_row"] == pytest.approx(1.0)
        # Saturated: full 8-row batches back to back at 9 ms each.
        assert out["sustainable_rows_per_s"] == pytest.approx(8 / 0.009,
                                                              rel=1e-3)
        # No traffic yet -> rows_per_request defaults to 1.
        assert out["sustainable_qps"] == pytest.approx(8 / 0.009, rel=1e-3)

    def test_observed_fit_overrides_seeds(self, fake_clock, obs_on):
        t = CapacityTracker(16, window_s=60)
        t.seed_dispatch_model(1, 100.0)  # a wildly wrong seed
        t.seed_dispatch_model(16, 200.0)
        fake_clock[0] += 5.0
        # Observed truth: w(r) = 2 + 0.5 r, across varied rows.
        for rows in (1, 4, 8, 16, 2, 12):
            t.note_dispatch(2.0 + 0.5 * rows, rows, rows, 16)
        out = t.export()
        m = out["dispatch_model"]
        assert m["source"] == "observed"
        assert m["a_ms"] == pytest.approx(2.0, abs=1e-6)
        assert m["b_ms_per_row"] == pytest.approx(0.5, abs=1e-6)
        # w(16) = 10 ms -> 1600 rows/s sustainable.
        assert out["sustainable_rows_per_s"] == pytest.approx(1600, rel=1e-3)

    def test_chunked_redispatch_clamps_occupancy_and_skips_fit(
            self, fake_clock, obs_on):
        # After an OOM halves max_batch mid-batch, the re-dispatch lands
        # here as ONE record of rows > max_batch covering several chunked
        # device calls: each chunk ran full (occupancy 1.0, never >1) and
        # the point is excluded from the w(r) = a + b*r fit — its wall
        # paid the intercept once per chunk, which the model can't
        # express.
        t = CapacityTracker(16, window_s=60)
        fake_clock[0] += 5.0
        for rows in (1, 4, 8, 16, 2, 12):  # truth: w(r) = 2 + 0.5 r
            t.note_dispatch(2.0 + 0.5 * rows, rows, rows, 16)
        # A 32-row chunked re-dispatch at the halved cap of 16: two
        # chunks, two intercepts — a wildly off-model wall.
        t.note_dispatch(2 * 2.0 + 0.5 * 32 + 100.0, 32, 32, 16)
        out = t.export()
        assert out["occupancy_mean"] <= 1.0  # clamped, not 32/16
        m = out["dispatch_model"]
        assert m["source"] == "observed"
        assert m["a_ms"] == pytest.approx(2.0, abs=1e-6)
        assert m["b_ms_per_row"] == pytest.approx(0.5, abs=1e-6)
        # The chunked dispatch still counts for duty/throughput/waste.
        assert out["dispatch_rows_per_s"] > 0

    def test_headroom_ratio_vs_arrival(self, fake_clock, obs_on):
        t = CapacityTracker(4, window_s=60)
        fake_clock[0] += 10.0
        t.seed_dispatch_model(1, 5.0)
        t.seed_dispatch_model(4, 8.0)  # w(4)=8ms -> 500 rows/s
        for _ in range(100):  # 10 req/s of 1-row arrivals
            t.note_arrival(1)
            t.note_served(1, 10.0)
        out = t.export()
        assert out["rows_per_request"] == pytest.approx(1.0)
        assert out["sustainable_qps"] == pytest.approx(500.0, rel=1e-3)
        assert out["headroom_ratio"] == pytest.approx(50.0, rel=1e-3)
        assert out["utilization"] == pytest.approx(10 / 500, rel=1e-3)

    def test_window_expires_old_traffic(self, fake_clock, obs_on):
        t = CapacityTracker(8, window_s=10)
        fake_clock[0] += 5.0
        t.note_arrival(1)
        assert t.export()["arrival_qps"] > 0
        fake_clock[0] += 30.0  # far past the 10 s window
        assert t.export()["arrival_qps"] == 0.0

    def test_gauges_exported(self, fake_clock, obs_on):
        t = CapacityTracker(8, window_s=60)
        fake_clock[0] += 2.0
        t.note_dispatch(10.0, 4, 128, 8)
        t.export()
        prom = obs_on.to_prometheus()
        for needle in ("knn_capacity_duty_cycle",
                       "knn_capacity_occupancy_mean",
                       "knn_capacity_batch_occupancy_bucket",
                       "knn_capacity_dispatch_rows_per_s"):
            assert needle in prom, needle

    def test_validation(self):
        with pytest.raises(ValueError, match="max_batch"):
            CapacityTracker(0)
        with pytest.raises(ValueError, match="window_s"):
            CapacityTracker(8, window_s=0)


class TestSecondRing:
    def test_field_count_enforced(self):
        r = SecondRing(2, 60)
        with pytest.raises(ValueError, match="field deltas"):
            r.add(1)

    def test_window_sums_float_fields(self, monkeypatch):
        import knn_tpu.obs.slo as slo_mod

        clock = [100.0]
        monkeypatch.setattr(slo_mod.time, "monotonic", lambda: clock[0])
        r = SecondRing(2, 30)
        r.add(1, 2.5)
        clock[0] += 3
        r.add(1, 1.5)
        assert r.window_sums(30) == (2, 4.0)
        assert r.window_sums(2) == (1, 1.5)

    def test_validation(self):
        with pytest.raises(ValueError, match="fields"):
            SecondRing(0, 60)
        with pytest.raises(ValueError, match="max_window_s"):
            SecondRing(1, 0)


# ---------------------------------------------------------------------------
# HTTP integration: class header, /debug/capacity, cost block in timelines


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _post(base, path, payload, headers=None):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


@pytest.fixture
def served_with_cost(rng, obs_on):
    """A warmed in-process server with cost accounting ON."""
    from knn_tpu.serve.server import ServeApp, make_server

    train, test = _problem(rng)
    model = KNNClassifier(k=3, engine="xla").fit(train)
    app = ServeApp(model, max_batch=16, max_wait_ms=1.0,
                   cost_accounting=True)
    server = make_server(app)
    host, port = server.server_address[:2]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    app.warm((1, 4))
    try:
        yield f"http://{host}:{port}", model, test, app
    finally:
        server.shutdown()
        server.server_close()
        app.close()
        thread.join(timeout=10)


class TestServerCostCapacity:
    def test_debug_capacity_joins_cost_and_headroom(self, served_with_cost):
        base, _, test, app = served_with_cost
        st, _, _ = _post(base, "/predict",
                         {"instances": test.features[:2].tolist()})
        assert st == 200
        st, body = _get(base, "/debug/capacity")
        assert st == 200
        doc = json.loads(body)
        assert doc["enabled"] is True
        assert doc["policy"]["max_batch"] == 16
        assert doc["cost"]["totals"]["dispatches"] >= 1
        assert doc["cost"]["totals"]["attributed_ms"] == pytest.approx(
            doc["cost"]["totals"]["dispatch_wall_ms"], rel=1e-9)
        assert "interactive" in doc["cost"]["classes"]
        cap = doc["capacity"]
        # Warmup seeded the dispatch model before any traffic arrived.
        assert cap["dispatch_model"]["source"] in ("seed", "observed")
        assert cap["sustainable_qps"] is not None and \
            cap["sustainable_qps"] > 0

    def test_healthz_carries_capacity_block(self, served_with_cost):
        base, _, _, _ = served_with_cost
        st, body = _get(base, "/healthz")
        assert st == 200
        h = json.loads(body)
        assert h["capacity"] is not None
        assert "duty_cycle" in h["capacity"]

    def test_200_timeline_carries_cost_block(self, served_with_cost):
        base, _, test, _ = served_with_cost
        st, _, hdrs = _post(
            base, "/predict", {"instances": test.features[:1].tolist()},
            headers={"x-request-id": "cost-probe-1",
                     "x-knn-class": "bulk"},
        )
        assert st == 200
        st, body = _get(base, "/debug/requests?id=cost-probe-1")
        assert st == 200
        tl = json.loads(body)["requests"][0]
        assert tl["request_class"] == "bulk"
        assert tl["cost"]["class"] == "bulk"
        assert tl["cost"]["device_ms"] > 0
        assert tl["cost"]["rungs"]

    def test_body_class_field_wins_over_header(self, served_with_cost):
        base, _, test, app = served_with_cost
        st, _, _ = _post(
            base, "/predict",
            {"instances": test.features[:1].tolist(), "class": "batchjob"},
            headers={"x-knn-class": "bulk"},
        )
        assert st == 200
        classes = app.accounting.export()["classes"]
        assert "batchjob" in classes and "bulk" not in classes

    def test_body_class_null_falls_back_to_header(self, served_with_cost):
        # Serializers that emit null for unset fields must not silently
        # discard the caller's x-knn-class tag: an explicit JSON null
        # reads like an absent field, not like "no class".
        base, _, test, app = served_with_cost
        st, _, _ = _post(
            base, "/predict",
            {"instances": test.features[:1].tolist(), "class": None},
            headers={"x-knn-class": "nullfallback"},
        )
        assert st == 200
        assert "nullfallback" in app.accounting.export()["classes"]

    def test_invalid_class_is_400(self, served_with_cost):
        base, _, test, _ = served_with_cost
        st, body, _ = _post(
            base, "/predict", {"instances": test.features[:1].tolist()},
            headers={"x-knn-class": "NOT VALID"},
        )
        assert st == 400
        assert "class" in body["error"]

    def test_metrics_expose_cost_and_capacity(self, served_with_cost):
        base, _, test, _ = served_with_cost
        _post(base, "/predict", {"instances": test.features[:1].tolist()})
        st, text = _get(base, "/metrics")
        assert st == 200
        for needle in ("knn_cost_device_ms_total",
                       "knn_cost_dispatch_wall_ms_total",
                       "knn_cost_requests_total",
                       "knn_capacity_duty_cycle"):
            assert needle in text, needle

    def test_off_reports_null_and_skips_class_parsing(self, rng, obs_on):
        from knn_tpu.serve.server import ServeApp, make_server

        train, test = _problem(rng)
        app = ServeApp(KNNClassifier(k=3, engine="xla").fit(train),
                       max_batch=8, max_wait_ms=1.0)
        assert app.accounting is None and app.capacity is None
        server = make_server(app)
        host, port = server.server_address[:2]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://{host}:{port}"
        try:
            app.warm((1,))
            # An invalid class header is NOT parsed (and so not rejected)
            # while the layer is off.
            st, _, _ = _post(
                base, "/predict", {"instances": test.features[:1].tolist()},
                headers={"x-knn-class": "NOT VALID"},
            )
            assert st == 200
            st, body = _get(base, "/debug/capacity")
            assert st == 200
            doc = json.loads(body)
            assert doc["enabled"] is False
            assert doc["capacity"] is None and doc["cost"] is None
            st, body = _get(base, "/healthz")
            assert json.loads(body)["capacity"] is None
        finally:
            server.shutdown()
            server.server_close()
            app.close()


class TestAccountantUnits:
    def test_attribute_nothing_on_empty_batch(self, obs_on):
        a = CostAccountant()
        a.attribute([], 5.0, rung="fast", rows=0, padded_rows=0)
        assert a.export()["totals"]["dispatches"] == 0

    def test_export_rung_breakdown(self, obs_on):
        a = CostAccountant()

        class R:
            def __init__(self, rows, cls):
                self.rows, self.request_class = rows, cls
                self.meta, self.trace = {}, None

        r1, r2 = R(1, "interactive"), R(3, "bulk")
        a.attribute([r1, r2], 8.0, rung="fast", rows=4, padded_rows=128,
                    nbytes=400, ok=False)
        a.attribute([r1, r2], 4.0, rung="oracle", rows=4, padded_rows=4,
                    nbytes=400, ok=True)
        exp = a.export()
        assert exp["totals"]["dispatch_wall_ms"] == pytest.approx(12.0)
        assert exp["totals"]["padded_rows"] == 132
        inter = exp["classes"]["interactive"]
        assert inter["rungs"]["fast"] == pytest.approx(2.0)
        assert inter["rungs"]["oracle"] == pytest.approx(1.0)
        # rows/bytes count on the answering (ok) attempt only.
        assert inter["rows"] == 1
        bulk = exp["classes"]["bulk"]
        assert bulk["rungs"]["fast"] == pytest.approx(6.0)
        assert bulk["rows"] == 3
        assert inter["bytes"] + bulk["bytes"] == 400
        assert r1.meta["cost"]["padded_rows_share"] == pytest.approx(
            (128 - 4) * 0.25)
        _assert_conservation(obs_on)

    def test_default_class_constant(self):
        assert acct.DEFAULT_CLASS == "interactive"
