"""ARFF ingest tests: dialect coverage (SURVEY.md §3.4) + fixture golden shapes."""

import math

import numpy as np
import pytest

from knn_tpu.data import pyarff
from knn_tpu.data.arff import load_arff
from tests import fixtures


def parse(text: str):
    return pyarff.parse_arff_lines(text.splitlines(), path="<test>")


class TestDialect:
    def test_basic_numeric(self):
        ds = parse(
            """@relation rel
@attribute a NUMERIC
@attribute b REAL
@attribute class NUMERIC
@data
1.5,2,0
3,4.25,1
"""
        )
        assert ds.relation == "rel"
        np.testing.assert_array_equal(
            ds.features, np.array([[1.5, 2], [3, 4.25]], np.float32)
        )
        np.testing.assert_array_equal(ds.labels, [0, 1])
        assert ds.num_classes == 2

    def test_case_insensitive_keywords(self):
        # Keyword matching is case-insensitive (arff_utils.cpp:29-43).
        ds = parse(
            "@RELATION r\n@ATTRIBUTE x numeric\n@Attribute class Numeric\n@DATA\n1,0\n"
        )
        assert ds.num_instances == 1

    def test_comments_and_blank_lines(self):
        # % comments at line start are skipped (arff_lexer.cpp:60-78).
        ds = parse(
            "% header comment\n@relation r\n\n@attribute x NUMERIC\n"
            "@attribute class NUMERIC\n% mid comment\n@data\n% data comment\n1,0\n"
        )
        assert ds.num_instances == 1

    def test_missing_value_is_nan(self):
        # '?' -> missing (arff_parser.cpp:139-141).
        ds = parse(
            "@relation r\n@attribute x NUMERIC\n@attribute y NUMERIC\n"
            "@attribute class NUMERIC\n@data\n?,2,0\n"
        )
        assert math.isnan(ds.features[0, 0])
        assert ds.features[0, 1] == 2

    def test_nominal_attribute(self):
        # Nominal {a,b,c} attrs (arff_parser.cpp:69-119) -> category index.
        ds = parse(
            "@relation r\n@attribute color {red, green, blue}\n"
            "@attribute class NUMERIC\n@data\ngreen,0\nred,1\n"
        )
        np.testing.assert_array_equal(ds.features[:, 0], [1.0, 0.0])
        assert ds.attributes[0].nominal_values == ["red", "green", "blue"]

    def test_quoted_values(self):
        # Quoted strings incl. spaces (arff_lexer.cpp:159-188).
        ds = parse(
            "@relation r\n@attribute c {'light red', 'dark blue'}\n"
            "@attribute class NUMERIC\n@data\n'dark blue',0\n"
        )
        assert ds.features[0, 0] == 1.0

    def test_quoted_attribute_name(self):
        ds = parse(
            "@relation r\n@attribute 'my attr' NUMERIC\n"
            "@attribute class NUMERIC\n@data\n1,0\n"
        )
        assert ds.attributes[0].name == "my attr"

    def test_partial_row_at_eof_discarded(self):
        # arff_parser.cpp:130-133,149-151.
        ds = parse(
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
            "@data\n1,0\n2\n"
        )
        assert ds.num_instances == 1

    def test_sparse_rejected(self):
        with pytest.raises(pyarff.ArffError, match="sparse"):
            parse(
                "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
                "@data\n{0 1, 1 0}\n"
            )

    def test_bad_number_has_location(self):
        with pytest.raises(pyarff.ArffError, match="<test>:5"):
            parse(
                "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
                "@data\nabc,0\n"
            )

    def test_extra_token_carries_into_next_row(self):
        # The @data section is a token stream (arff_parser.cpp:121-153):
        # "1,2,3" with two attributes is row (1,2) plus a pending token that
        # the next line completes — or that EOF discards.
        ds = parse(
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
            "@data\n1,2,3\n"
        )
        np.testing.assert_array_equal(ds.features, [[1.0]])
        np.testing.assert_array_equal(ds.labels, [2])
        ds = parse(
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
            "@data\n1,2,3\n4\n"
        )
        np.testing.assert_array_equal(ds.features, [[1.0], [3.0]])
        np.testing.assert_array_equal(ds.labels, [2, 4])

    def test_whitespace_separates_tokens(self):
        # The reference lexer treats unquoted whitespace exactly like a comma
        # separator (next_token skips it between tokens): "1 2" is a 2-value
        # row and "1,2 3,4" is TWO rows on one line.
        ds = parse(
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
            "@data\n1 2\n"
        )
        np.testing.assert_array_equal(ds.features, [[1.0]])
        np.testing.assert_array_equal(ds.labels, [2])
        ds = parse(
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
            "@data\n1,2 3,4\n"
        )
        np.testing.assert_array_equal(ds.features, [[1.0], [3.0]])
        np.testing.assert_array_equal(ds.labels, [2, 4])

    def test_interior_cr_is_a_token_char(self, tmp_path):
        # The reference scanner's NEWLINE is '\n' alone (arff_scanner.cpp:4)
        # and '\r' is not lexer whitespace (arff_lexer.cpp:28), so an
        # interior '\r' belongs to its token — both parsers must agree
        # (universal-newline file reading used to split pyarff lines at a
        # lone '\r'). CRLF line endings still parse (trailing '\r' strips).
        bad = tmp_path / "cr.arff"
        bad.write_bytes(
            b"@relation t\n@attribute a NUMERIC\n@attribute class NUMERIC\n"
            b"@data\n1\r2,0\n3,1\n"
        )
        with pytest.raises(pyarff.ArffError, match=r"cannot parse '1\r2'"):
            pyarff.parse_arff_file(str(bad))
        crlf = tmp_path / "crlf.arff"
        crlf.write_bytes(
            b"@relation t\r\n@attribute a NUMERIC\r\n"
            b"@attribute class NUMERIC\r\n@data\r\n1,0\r\n3,1\r\n"
        )
        ds = pyarff.parse_arff_file(str(crlf))
        np.testing.assert_array_equal(ds.features, [[1.0], [3.0]])
        np.testing.assert_array_equal(ds.labels, [0, 1])

    def test_numeric_fast_path_bitwise_matches_slow(self, tmp_path, rng):
        # The vectorized all-numeric fast path must be bitwise identical to
        # the token-by-token parser across separator styles, multi-line and
        # shared-line rows, ragged whitespace, and a partial trailing row.
        from knn_tpu.data.pyarff import _parse_numeric_fast, parse_arff_lines

        for trial in range(20):
            d = int(rng.integers(2, 6))
            n = int(rng.integers(1, 40))
            vals = (rng.normal(0, 10, (n, d)) * 10.0 **
                    rng.integers(-6, 7, (n, d))).astype(np.float32)
            vals[:, -1] = rng.integers(0, 5, n)
            toks = [repr(float(v)) if rng.random() < 0.5 else f"{v:.6g}"
                    for v in vals.ravel()]
            body, line = [], []
            for tk in toks:
                line.append(tk + (rng.choice([",", " ", ",\t"])))
                if rng.random() < 0.3:
                    body.append("".join(line))
                    line = []
            body.append("".join(line))
            if rng.random() < 0.5:
                body.append("0.5 1")  # partial trailing row: discarded
            hdr = ["@relation r"] + [f"@attribute a{j} NUMERIC" for j in range(d - 1)] \
                + ["@attribute class NUMERIC", "@data"]
            raw = "\n".join(hdr + body) + "\n"
            fast = _parse_numeric_fast(raw, "<t>")
            slow = parse_arff_lines(raw.split("\n"), "<t>")
            assert fast is not None, f"trial {trial} fell back unexpectedly"
            np.testing.assert_array_equal(
                fast.features.view(np.uint32), slow.features.view(np.uint32))
            np.testing.assert_array_equal(fast.labels, slow.labels)
            np.testing.assert_array_equal(
                fast.raw_targets.view(np.uint32),
                slow.raw_targets.view(np.uint32))

    def test_fast_path_defers_dialect_subtleties(self, tmp_path):
        # Files with quotes / comments / missing values / empty cells /
        # sparse braces / nominal attrs must take the full parser.
        from knn_tpu.data.pyarff import _parse_numeric_fast

        hdr = ("@relation r\n@attribute x NUMERIC\n"
               "@attribute class NUMERIC\n@data\n")
        for body in ("'1',0\n", "% c\n1,0\n", "?,0\n", "1,,0\n", ",1\n",
                     "{0 1},0\n"):
            assert _parse_numeric_fast(hdr + body, "<t>") is None, body
        nom = ("@relation r\n@attribute c {a,b}\n"
               "@attribute class NUMERIC\n@data\na,0\n")
        assert _parse_numeric_fast(nom, "<t>") is None

    def test_fast_path_ignores_data_inside_quoted_header_value(self):
        # An '@data' line can lie INSIDE a multi-line quoted header value
        # (quoted values span physical lines, arff_lexer.cpp:159-188). The
        # fast path must not anchor on it: truncating the header there ends
        # mid-quote and would raise 'unterminated quoted value' on a file
        # both full parsers load fine (round-3 advisor repro).
        from knn_tpu.data.pyarff import _parse_numeric_fast, parse_arff_lines

        raw = ("@relation 'x\n@data y'\n@attribute a NUMERIC\n"
               "@attribute class NUMERIC\n@data\n1,2\n")
        assert _parse_numeric_fast(raw, "<t>") is None  # no spurious raise
        ds = parse_arff_lines(raw.split("\n"), path="<t>")
        assert ds.relation == "x\n@data y"
        np.testing.assert_array_equal(ds.features, [[1.0]])
        np.testing.assert_array_equal(ds.labels, [2])
        # A quoted header value that CLOSES before the real @data keeps the
        # fast path (state scan is exact, not just conservative).
        ok = ("@relation 'multi\nline name'\n@attribute a NUMERIC\n"
              "@attribute class NUMERIC\n@data\n1,2\n")
        fast = _parse_numeric_fast(ok, "<t>")
        assert fast is not None and fast.relation == "multi\nline name"

    def test_fast_path_ignores_data_inside_open_nominal_list(self):
        # Same defect class through the OTHER multi-line header construct:
        # an '@data' line inside an open {...} nominal list (newlines are
        # whitespace between value tokens, arff_parser.cpp:69-119) must not
        # anchor the fast path either — truncating there raises
        # 'unterminated nominal value list' on a file the full parser loads.
        from knn_tpu.data.pyarff import _parse_numeric_fast, parse_arff_lines

        raw = ("@relation r\n@attribute a {x,\n@data\ny}\n"
               "@attribute class NUMERIC\n@data\nx,1\n")
        assert _parse_numeric_fast(raw, "<t>") is None  # no spurious raise
        ds = parse_arff_lines(raw.split("\n"), path="<t>")
        assert [a.name for a in ds.attributes] == ["a", "class"]
        assert ds.attributes[0].nominal_values == ["x", "@data", "y"]
        np.testing.assert_array_equal(ds.labels, [1])

    def test_fast_path_defers_quote_opened_on_data_line(self):
        # A quote opened by the @data line's OWN trailing content joins the
        # first data row into the header's logical line in the full parser
        # (which then errors at EOF); the fast path must not silently
        # succeed there — the scan covers through the end of the @data line.
        from knn_tpu.data.pyarff import _parse_numeric_fast, parse_arff_lines

        raw = ("@relation r\n@attribute a NUMERIC\n"
               "@attribute class NUMERIC\n@data '\n1,2\n")
        assert _parse_numeric_fast(raw, "<t>") is None
        with pytest.raises(pyarff.ArffError, match="unterminated"):
            parse_arff_lines(raw.split("\n"), path="<t>")

    def test_indented_percent_is_data_not_comment(self):
        # '%' starts a comment only at the true line start
        # (arff_lexer.cpp:60-78); indented it is a data token, which fails
        # numeric conversion with a located error (the reference throws a
        # type error for the same input).
        with pytest.raises(pyarff.ArffError):
            parse(
                "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
                "@data\n % not a comment\n1,2\n"
            )

    def test_unknown_nominal_value(self):
        with pytest.raises(pyarff.ArffError, match="not in nominal set"):
            parse(
                "@relation r\n@attribute c {a,b}\n@attribute class NUMERIC\n"
                "@data\nz,0\n"
            )

    def test_missing_class_rejected(self):
        with pytest.raises(pyarff.ArffError, match="missing class"):
            parse(
                "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
                "@data\n1,?\n"
            )


class TestFixtures:
    def test_shapes(self, small, medium, large):
        expect = {
            "small": (592, 80, 7),
            "medium": (7354, 370, 11),
            "large": (30803, 1718, 11),
        }
        for name, (train, test) in zip(
            ["small", "medium", "large"], [small, medium, large]
        ):
            n, q, d = expect[name]
            assert train.features.shape == (n, d)
            assert test.features.shape == (q, d)
            assert train.num_classes == 10
            assert train.features.dtype == np.float32
            assert train.labels.dtype == np.int32

    def test_sentinel_rows_pin_num_classes(self, large):
        train, test = large
        # First rows carry sentinel labels (SURVEY.md §2.4).
        assert train.num_classes == 10
        assert test.num_classes == 10

    def test_large_test_subset_of_train(self, large):
        # dist==0 ties are real in the headline config (SURVEY.md §2.4).
        if not fixtures.using_reference_datasets():
            pytest.skip("synthetic fixtures: only half the test set duplicates train")
        train, test = large
        train_rows = {r.tobytes() for r in train.features}
        assert all(r.tobytes() in train_rows for r in test.features)


class TestWriteArff:
    """write_arff — the capability the reference declares but never implements
    (libarff/arff_data.h:131, arff_data.cpp:167)."""

    def test_roundtrip_fixture(self, small, tmp_path):
        from knn_tpu.data.arff import load_arff, write_arff

        train, _ = small
        out = tmp_path / "rt.arff"
        write_arff(train, str(out))
        back = load_arff(str(out))
        np.testing.assert_array_equal(back.features, train.features)
        np.testing.assert_array_equal(back.labels, train.labels)
        assert back.num_classes == train.num_classes

    def test_roundtrip_nan_and_nominal(self, tmp_path):
        from knn_tpu.data.arff import load_arff, write_arff
        from knn_tpu.data.dataset import Attribute, Dataset

        ds = Dataset(
            features=np.array([[1.5, 0.0], [np.nan, 1.0]], np.float32),
            labels=np.array([0, 2], np.int32),
            relation="with space",
            attributes=[
                Attribute("x", "numeric"),
                Attribute("color", "nominal", ["red", "green"]),
                Attribute("class", "numeric"),
            ],
        )
        out = tmp_path / "rt.arff"
        write_arff(ds, str(out))
        back = load_arff(str(out))
        np.testing.assert_array_equal(back.labels, ds.labels)
        assert np.isnan(back.features[1, 0])
        np.testing.assert_array_equal(back.features[:, 1], ds.features[:, 1])
        assert back.relation == "with space"
        assert back.attributes[1].nominal_values == ["red", "green"]

    def test_roundtrip_spaced_nominal_and_string(self, tmp_path):
        # Nominal/string values with embedded spaces must be quoted in both
        # the declaration and the data cells or the whitespace tokenizer
        # splits them on re-read (r2 review).
        from knn_tpu.data.arff import load_arff, write_arff
        from knn_tpu.data.dataset import Attribute, Dataset

        ds = Dataset(
            features=np.array([[0.0, 0.0], [1.0, 1.0]], np.float32),
            labels=np.array([1, 2], np.int32),
            attributes=[
                Attribute("c", "nominal", ["dark red", "pale, blue"]),
                Attribute("s", "string", string_values=["a b", "x"]),
                Attribute("class", "numeric"),
            ],
        )
        out = tmp_path / "rt.arff"
        write_arff(ds, str(out))
        back = load_arff(str(out))
        np.testing.assert_array_equal(back.features, ds.features)
        assert back.attributes[0].nominal_values == ["dark red", "pale, blue"]
        assert back.attributes[1].string_values == ["a b", "x"]

    def test_roundtrip_comment_and_sparse_lookalike_values(self, tmp_path):
        # A bare first-column value starting with % re-reads as a comment
        # (silently dropping the row) and one starting with { as a sparse
        # row (hard error) — both must be quoted on write (r2 review).
        from knn_tpu.data.arff import load_arff, write_arff
        from knn_tpu.data.dataset import Attribute, Dataset

        ds = Dataset(
            features=np.array([[0.0], [1.0], [2.0]], np.float32),
            labels=np.array([0, 1, 0], np.int32),
            attributes=[
                Attribute("s", "string", string_values=["%pct", "{brace", "@at"]),
                Attribute("class", "numeric"),
            ],
        )
        out = tmp_path / "rt.arff"
        write_arff(ds, str(out))
        back = load_arff(str(out))
        np.testing.assert_array_equal(back.features, ds.features)
        assert back.attributes[0].string_values == ["%pct", "{brace", "@at"]

    def test_question_mark_value_unrepresentable(self, tmp_path):
        # The dialect strips quotes before the missing-value check (same as
        # the reference lexer), so a string/nominal value "?" cannot survive
        # a round trip — write_arff must raise instead of silently writing a
        # cell that re-ingests as NaN and shifts later intern codes
        # (r2 review).
        from knn_tpu.data.arff import write_arff
        from knn_tpu.data.dataset import Attribute, Dataset

        for attr in (
            Attribute("s", "string", string_values=["?", "x"]),
            Attribute("c", "nominal", ["?", "x"]),
        ):
            ds = Dataset(
                features=np.array([[0.0], [1.0]], np.float32),
                labels=np.array([0, 1], np.int32),
                attributes=[attr, Attribute("class", "numeric")],
            )
            with pytest.raises(ValueError, match="missing value"):
                write_arff(ds, str(tmp_path / "bad.arff"))

    def test_attr_mismatch_rejected(self, tmp_path):
        from knn_tpu.data.arff import write_arff
        from knn_tpu.data.dataset import Attribute, Dataset

        ds = Dataset(
            features=np.zeros((1, 2), np.float32),
            labels=np.zeros(1, np.int32),
            attributes=[Attribute("only-one", "numeric")],
        )
        with pytest.raises(ValueError):
            write_arff(ds, str(tmp_path / "bad.arff"))
