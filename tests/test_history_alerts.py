"""Durable metrics history + declarative alerting contract tests
(docs/OBSERVABILITY.md §History & alerting).

The load-bearing claims:

* the on-disk segment ring round-trips the registry EXACTLY — counters
  reconstruct to absolute values from deltas, histograms through
  ``Histogram.merge_counts`` with raw bucket counts, never a lossy
  pre-sum — and every segment decodes independently so retention can
  drop whole segments;
* crash-safety mirrors the mutable WAL tail: a torn final line of the
  last segment is tolerated and repaired in place, damage anywhere else
  is a typed ``DataError``;
* every rule type's hysteresis machine (ok → pending → firing →
  resolving → ok) emits exactly ONE fire/resolve audit pair per
  incident, with flaps while resolving snapping back silently;
* actions dispatch off-thread, are audited including raises, and a
  broken action never takes the engine down;
* the post-mortem CLI answers a range query from a dead process's dir,
  and ``build_report`` is deterministic — byte-identical on re-run.

Everything runs on an injectable fake clock; no sleeps, no wall time.
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier
from knn_tpu.obs.alerts import AlertEngine, load_rules, parse_rules
from knn_tpu.obs.history import (
    SCHEMA_HASH, HistoryRecorder, load_history, parse_window, query_samples,
)
from knn_tpu.obs.report import build_report, render_markdown
from knn_tpu.resilience.errors import DataError


@pytest.fixture
def obs_on():
    """Enabled + isolated observability for metric assertions."""
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def snap(counter=None, gauge=None, hist=None):
    """A fake ``aggregate.snapshot_registry()`` listing: one counter
    (labelled), one gauge, one 3-bound histogram (counts length 4 with
    the +Inf overflow slot)."""
    recs = []
    if counter is not None:
        recs.append({"name": "t_requests_total", "kind": "counter",
                     "labels": {"kind": "predict"}, "help": "",
                     "value": float(counter)})
    if gauge is not None:
        recs.append({"name": "t_depth", "kind": "gauge", "labels": {},
                     "help": "", "value": float(gauge)})
    if hist is not None:
        counts, s, c = hist
        recs.append({"name": "t_ms", "kind": "histogram", "labels": {},
                     "help": "", "buckets": [1.0, 5.0, 25.0],
                     "counts": list(counts), "sum": float(s),
                     "count": int(c)})
    return recs


def make_recorder(feed, clock, history_dir=None, **kw):
    kw.setdefault("interval_s", 1.0)
    kw.setdefault("retention_s", 60.0)
    return HistoryRecorder(history_dir, sample_fn=lambda: feed[0],
                           clock=clock, autostart=False, **kw)


class TestParseWindow:
    def test_units(self):
        assert parse_window("300") == 300.0
        assert parse_window("300s") == 300.0
        assert parse_window("5m") == 300.0
        assert parse_window("1h") == 3600.0
        assert parse_window(45) == 45.0

    def test_bad_values(self):
        for raw in ("abc", "5x", "", "0", "-3", "0s"):
            with pytest.raises(ValueError):
                parse_window(raw)


class TestRoundTrip:
    def test_counter_gauge_histogram_reconstruct_exactly(self, tmp_path):
        clock = FakeClock()
        feed = [snap(counter=1, gauge=4, hist=([1, 0, 0, 0], 0.5, 1))]
        rec = make_recorder(feed, clock, str(tmp_path / "h"))
        rec.sample_once()
        clock.advance(1)
        feed[0] = snap(counter=3, gauge=2, hist=([1, 2, 0, 1], 40.5, 4))
        rec.sample_once()
        clock.advance(1)
        feed[0] = snap(counter=6, gauge=2, hist=([1, 2, 3, 1], 70.5, 7))
        rec.sample_once()

        hist = load_history(str(tmp_path / "h"))
        assert not hist.repaired
        assert len(hist.samples) == 3
        # Counters come back ABSOLUTE even though the wire is deltas.
        got = hist.query(metric="t_requests_total")["series"][0]
        assert got["kind"] == "counter"
        assert got["labels"] == {"kind": "predict"}
        assert [p[1] for p in got["points"]] == [1.0, 3.0, 6.0]
        # Gauges: absolute, present at every sample they held a value.
        got = hist.query(metric="t_depth")["series"][0]
        assert [p[1] for p in got["points"]] == [4.0, 2.0, 2.0]
        # Histograms: raw bucket counts through merge_counts — count,
        # sum, AND the per-bucket distribution all exact.
        got = hist.query(metric="t_ms")["series"][0]
        assert got["kind"] == "histogram"
        assert [p[1] for p in got["points"]] == [1, 4, 7]  # count
        assert got["points"][-1][2] == 70.5  # sum
        assert got["counts"] == [1, 2, 3, 1]  # final raw buckets
        assert got["buckets"] == [1.0, 5.0, 25.0]

    def test_wire_is_delta_encoded(self, tmp_path):
        clock = FakeClock()
        feed = [snap(counter=5, gauge=1)]
        rec = make_recorder(feed, clock, str(tmp_path / "h"))
        rec.sample_once()
        clock.advance(1)
        feed[0] = snap(counter=9, gauge=1)  # counter +4, gauge unchanged
        rec.sample_once()
        clock.advance(1)
        rec.sample_once()  # nothing changed at all

        seg = tmp_path / "h" / "seg-1.jsonl"
        lines = [json.loads(ln) for ln in
                 seg.read_text().splitlines()]
        header, base, d1, d2 = lines
        assert header["schema_hash"] == SCHEMA_HASH
        assert base["d"] == 0
        counter_base = next(e for e in base["m"] if e["n"] == "t_requests_total")
        assert counter_base["v"] == 5.0
        assert d1["d"] == 1
        # Delta record: the counter increment only — the unchanged gauge
        # is omitted entirely.
        assert [e["n"] for e in d1["m"]] == ["t_requests_total"]
        assert d1["m"][0]["v"] == 4.0
        assert d2["m"] == []  # quiet process: bytes ~ nothing

    def test_segments_decode_independently(self, tmp_path):
        # rotate_s = max(1, 16/8) = 2 -> a new segment every 2 samples.
        clock = FakeClock()
        feed = [snap(counter=0)]
        rec = make_recorder(feed, clock, str(tmp_path / "h"),
                            retention_s=16.0)
        for i in range(6):
            feed[0] = snap(counter=10 * (i + 1))
            rec.sample_once()
            clock.advance(1)
        segs = sorted(p.name for p in (tmp_path / "h").glob("seg-*.jsonl"))
        assert len(segs) >= 2
        # Drop the FIRST segment: later ones must still decode to the
        # correct absolute values (each opens with a base record).
        (tmp_path / "h" / segs[0]).unlink()
        hist = load_history(str(tmp_path / "h"))
        pts = hist.query(metric="t_requests_total")["series"][0]["points"]
        assert pts[-1][1] == 60.0


class TestRotationRetention:
    def test_rotation_and_retention_prune_whole_segments(self, tmp_path):
        clock = FakeClock()
        feed = [snap(counter=0)]
        rec = make_recorder(feed, clock, str(tmp_path / "h"),
                            retention_s=8.0)  # rotate_s = 1s
        for i in range(20):
            feed[0] = snap(counter=i)
            rec.sample_once()
            clock.advance(1)
        status = rec.status()
        assert status["pruned_segments"] >= 1
        live = sorted(int(p.stem.split("-")[1])
                      for p in (tmp_path / "h").glob("seg-*.jsonl"))
        # Only segments inside the retention window survive on disk.
        assert live[0] > 1
        hist = load_history(str(tmp_path / "h"))
        span = hist.samples[-1][0] - hist.samples[0][0]
        assert span <= 8.0 + 1.0
        # The live ring answers the same trailing window.
        live_q = rec.query(metric="t_requests_total", window_s=5)
        assert live_q["series"][0]["points"]

    def test_flag_validation(self, tmp_path):
        with pytest.raises(ValueError):
            make_recorder([[]], FakeClock(), interval_s=0)
        with pytest.raises(ValueError):
            make_recorder([[]], FakeClock(), interval_s=5, retention_s=1)


class TestTornTail:
    def _write_history(self, tmp_path, n=3):
        clock = FakeClock()
        feed = [snap(counter=0)]
        rec = make_recorder(feed, clock, str(tmp_path / "h"))
        for i in range(n):
            feed[0] = snap(counter=i + 1)
            rec.sample_once()
            clock.advance(1)
        return tmp_path / "h"

    def test_torn_final_line_tolerated_and_repaired(self, tmp_path):
        h = self._write_history(tmp_path)
        seg = h / "seg-1.jsonl"
        with open(seg, "a", encoding="utf-8") as f:
            f.write('{"t": 1003.0, "d": 1, "m"')  # crash mid-append
        hist = load_history(str(h))
        assert hist.repaired
        assert len(hist.samples) == 3
        # The repair is durable: the torn line is GONE from disk.
        assert all(json.loads(ln) for ln in seg.read_text().splitlines())
        assert not load_history(str(h)).repaired

    def test_mid_file_corruption_refused(self, tmp_path):
        h = self._write_history(tmp_path)
        seg = h / "seg-1.jsonl"
        lines = seg.read_text().splitlines()
        lines[2] = '{"t": broken'
        seg.write_text("\n".join(lines) + "\n")
        with pytest.raises(DataError):
            load_history(str(h))

    def test_torn_tail_of_non_last_segment_refused(self, tmp_path):
        clock = FakeClock()
        feed = [snap(counter=0)]
        rec = make_recorder(feed, clock, str(tmp_path / "h"),
                            retention_s=16.0)  # rotate every 2 samples
        for i in range(5):
            feed[0] = snap(counter=i)
            rec.sample_once()
            clock.advance(1)
        segs = sorted((tmp_path / "h").glob("seg-*.jsonl"))
        assert len(segs) >= 2
        with open(segs[0], "a", encoding="utf-8") as f:
            f.write('{"torn')
        with pytest.raises(DataError):
            load_history(str(tmp_path / "h"))

    def test_schema_hash_pin_refuses_foreign_segments(self, tmp_path):
        h = self._write_history(tmp_path)
        seg = h / "seg-1.jsonl"
        lines = seg.read_text().splitlines()
        header = json.loads(lines[0])
        header["schema_hash"] = "0" * 32
        lines[0] = json.dumps(header)
        seg.write_text("\n".join(lines) + "\n")
        with pytest.raises(DataError, match="incompatible"):
            load_history(str(h))

    def test_boot_scan_repairs_and_opens_fresh_segment(self, tmp_path):
        h = self._write_history(tmp_path)
        with open(h / "seg-1.jsonl", "a", encoding="utf-8") as f:
            f.write('{"t": 99')  # the predecessor was SIGKILLed
        clock = FakeClock(1010.0)  # restart inside the retention window
        feed = [snap(counter=100)]
        rec = make_recorder(feed, clock, str(h))
        rec.sample_once()
        assert rec.status()["segment"] == 2  # never appends to the tail
        hist = load_history(str(h))
        assert not hist.repaired  # boot already repaired it
        assert [int(n) for n in hist.segments] == [1, 2]
        assert hist.samples[-1][0] == 1010.0


class TestQuerySamples:
    def test_window_and_label_filters(self):
        state = lambda v, extra=None: {  # noqa: E731 — tiny local builder
            ("m", (("az", "a"),)): ("c", "m", {"az": "a"}, v),
            **(extra or {})}
        samples = [(1000.0, state(1.0)), (1001.0, state(2.0)),
                   (1002.0, state(3.0,
                    {("m", (("az", "b"),)): ("c", "m", {"az": "b"}, 9.0)}))]
        doc = query_samples(samples, metric="m", labels={"az": "a"},
                            window_s=1.0)
        assert doc["window"] == {"from": 1001.0, "to": 1002.0}
        assert len(doc["series"]) == 1
        assert [p[1] for p in doc["series"][0]["points"]] == [2.0, 3.0]
        # No filters: both labelled series come back, sorted.
        assert len(query_samples(samples)["series"]) == 2


class TestRuleParsing:
    def test_normalization_defaults(self):
        rules = parse_rules({"rules": [
            {"name": "a", "type": "threshold", "metric": "m", "value": 3,
             "for_s": 2},
            {"name": "b", "type": "burn_rate", "threshold": 1.5,
             "actions": [{"do": "capture"}]},
        ]})
        assert rules[0]["op"] == ">"
        assert rules[0]["resolve_for_s"] == 2.0  # defaults to for_s
        assert rules[1]["objective"] == "availability"
        assert rules[1]["windows"] is None
        # A capture action with neither bound gets the default window.
        assert rules[1]["actions"] == [{"do": "capture", "window_s": 10.0}]

    def test_shape_errors_are_typed(self):
        bad = [
            {},  # not a list
            [],  # empty
            [{"type": "threshold"}],  # no name
            [{"name": "x", "type": "nope"}],
            [{"name": "x", "type": "threshold", "metric": "m", "value": 1},
             {"name": "x", "type": "threshold", "metric": "m", "value": 1}],
            [{"name": "x", "type": "threshold", "metric": "m",
              "value": 1, "op": "!="}],
            [{"name": "x", "type": "threshold", "value": 1}],  # no metric
            [{"name": "x", "type": "threshold", "metric": "m",
              "value": "high"}],
            [{"name": "x", "type": "burn_rate", "threshold": 0}],
            [{"name": "x", "type": "burn_rate", "threshold": 1,
              "windows": []}],
            [{"name": "x", "type": "derivative", "metric": "m", "value": 1}],
            [{"name": "x", "type": "absence"}],
            [{"name": "x", "type": "threshold", "metric": "m", "value": 1,
              "for_s": -1}],
            [{"name": "x", "type": "threshold", "metric": "m", "value": 1,
              "actions": [{"do": "explode"}]}],
            [{"name": "x", "type": "threshold", "metric": "m", "value": 1,
              "actions": [{"do": "command", "cmd": "  "}]}],
            [{"name": "x", "type": "threshold", "metric": "m", "value": 1,
              "actions": [{"do": "capture", "max_requests": 0}]}],
        ]
        for doc in bad:
            with pytest.raises(DataError):
                parse_rules(doc)

    def test_load_rules_file_errors(self, tmp_path):
        with pytest.raises(DataError):
            load_rules(str(tmp_path / "missing.json"))
        p = tmp_path / "rules.json"
        p.write_text("{not json")
        with pytest.raises(DataError):
            load_rules(str(p))


class _StubSLO:
    def __init__(self):
        self.burns = {"availability": {"5s": 0.0, "1m": 0.0}}

    def burn_rates(self):
        return {k: dict(v) for k, v in self.burns.items()}


def _engine(rules, clock, **kw):
    return AlertEngine(parse_rules(rules), clock=clock, **kw)


def _step(feed, rec, engine, clock, dt=1.0, **snap_kw):
    if snap_kw:
        feed[0] = snap(**snap_kw)
    ts = rec.sample_once()
    engine.evaluate(ts, rec)
    clock.advance(dt)
    return ts


def _events(engine, kind):
    return [e for e in engine.export()["recent"] if e.get("event") == kind]


class TestAlertHysteresis:
    def test_threshold_for_flap_resolve_single_pair(self, obs_on):
        clock = FakeClock()
        feed = [snap(gauge=10)]
        rec = make_recorder(feed, clock)
        eng = _engine([{"name": "hot", "type": "threshold",
                        "metric": "t_depth", "op": ">", "value": 5,
                        "for_s": 2, "resolve_for_s": 2}], clock)
        _step(feed, rec, eng, clock)  # t=1000: pending
        assert eng.export()["rules"][0]["state"] == "pending"
        assert not _events(eng, "fire")
        _step(feed, rec, eng, clock)  # t=1001: held 1s < for_s
        _step(feed, rec, eng, clock)  # t=1002: held 2s -> FIRE
        assert len(_events(eng, "fire")) == 1
        assert eng.export()["firing"] == ["hot"]
        assert obs_on.gauge("knn_alerts_firing", alert="hot").value == 1
        _step(feed, rec, eng, clock, gauge=1)  # t=1003: resolving
        assert eng.export()["rules"][0]["state"] == "resolving"
        assert "hot" in eng.export()["firing"]  # resolving still pages
        _step(feed, rec, eng, clock, gauge=10)  # t=1004: FLAP back
        assert eng.export()["rules"][0]["state"] == "firing"
        assert len(_events(eng, "fire")) == 1  # NO second fire event
        _step(feed, rec, eng, clock, gauge=1)  # t=1005: resolving again
        _step(feed, rec, eng, clock)  # t=1006: held 1s
        assert not _events(eng, "resolve")
        _step(feed, rec, eng, clock)  # t=1007: held 2s -> RESOLVE
        assert len(_events(eng, "resolve")) == 1
        assert eng.export()["rules"][0]["state"] == "ok"
        assert eng.export()["rules"][0]["fires"] == 1
        assert obs_on.gauge("knn_alerts_firing", alert="hot").value == 0
        fire, = _events(eng, "fire")
        assert fire["alert"] == "hot" and fire["value"] == 10.0
        assert fire["severity"] == "page" and fire["type"] == "threshold"

    def test_condition_blip_shorter_than_for_never_fires(self, obs_on):
        clock = FakeClock()
        feed = [snap(gauge=10)]
        rec = make_recorder(feed, clock)
        eng = _engine([{"name": "hot", "type": "threshold",
                        "metric": "t_depth", "value": 5, "for_s": 2}], clock)
        _step(feed, rec, eng, clock)  # pending
        _step(feed, rec, eng, clock, gauge=1)  # back to ok before for_s
        _step(feed, rec, eng, clock, gauge=10)  # pending restarts from 0
        _step(feed, rec, eng, clock, gauge=1)
        assert not _events(eng, "fire")

    def test_for_zero_fires_immediately(self, obs_on):
        clock = FakeClock()
        feed = [snap(gauge=10)]
        rec = make_recorder(feed, clock)
        eng = _engine([{"name": "hot", "type": "threshold",
                        "metric": "t_depth", "value": 5}], clock)
        _step(feed, rec, eng, clock)
        assert len(_events(eng, "fire")) == 1

    def test_absence_rule(self, obs_on):
        clock = FakeClock()
        feed = [snap(gauge=1)]  # the counter is absent
        rec = make_recorder(feed, clock)
        eng = _engine([{"name": "silent", "type": "absence",
                        "metric": "t_requests_total"}], clock)
        _step(feed, rec, eng, clock)
        assert len(_events(eng, "fire")) == 1
        _step(feed, rec, eng, clock, counter=1, gauge=1)  # it's back
        assert len(_events(eng, "resolve")) == 1

    def test_derivative_rule(self, obs_on):
        clock = FakeClock()
        feed = [snap(counter=0)]
        rec = make_recorder(feed, clock)
        eng = _engine([{"name": "spike", "type": "derivative",
                        "metric": "t_requests_total", "op": ">",
                        "value": 2.0, "window_s": 2.0}], clock)
        _step(feed, rec, eng, clock)  # t=1000: no lookback yet
        _step(feed, rec, eng, clock, counter=5)  # t=1001: still short
        assert not _events(eng, "fire")
        _step(feed, rec, eng, clock, counter=10)  # t=1002: 10/2s = 5 > 2
        assert len(_events(eng, "fire")) == 1
        assert _events(eng, "fire")[0]["value"] == 5.0
        # Rate back under the line -> resolve.
        _step(feed, rec, eng, clock, counter=10)
        _step(feed, rec, eng, clock, counter=10)
        assert len(_events(eng, "resolve")) == 1

    def test_burn_rate_multi_window_and(self, obs_on):
        clock = FakeClock()
        slo = _StubSLO()
        feed = [snap(counter=1)]
        rec = make_recorder(feed, clock)
        eng = _engine([{"name": "burn", "type": "burn_rate",
                        "objective": "availability",
                        "windows": ["5s", "1m"], "threshold": 1.0}],
                      clock, slo=slo)
        slo.burns["availability"] = {"5s": 3.0, "1m": 0.5}
        _step(feed, rec, eng, clock)  # only ONE window burns: no fire
        assert not _events(eng, "fire")
        slo.burns["availability"] = {"5s": 3.0, "1m": 2.0}
        _step(feed, rec, eng, clock)  # both windows -> fire, value = max
        fire, = _events(eng, "fire")
        assert fire["value"] == 3.0
        slo.burns["availability"] = {"5s": 0.0, "1m": 0.0}
        _step(feed, rec, eng, clock)
        assert len(_events(eng, "resolve")) == 1

    def test_burn_rate_needs_slo_at_boot(self):
        with pytest.raises(DataError, match="burn_rate"):
            _engine([{"name": "b", "type": "burn_rate", "threshold": 1}],
                    FakeClock())

    def test_unknown_window_audited_not_raised(self, obs_on):
        clock = FakeClock()
        slo = _StubSLO()
        feed = [snap(counter=1)]
        rec = make_recorder(feed, clock)
        eng = _engine([{"name": "b", "type": "burn_rate",
                        "windows": ["7d"], "threshold": 1}], clock, slo=slo)
        _step(feed, rec, eng, clock)
        errs = _events(eng, "eval-error")
        assert errs and errs[0]["alert"] == "b"
        assert eng.export()["rules"][0]["state"] == "ok"


class _StubWorkload:
    def __init__(self, raise_on_start=False):
        self.started = []
        self.raise_on_start = raise_on_start

    def start(self, reason="manual", max_requests=None, window_s=None):
        if self.raise_on_start:
            raise RuntimeError("capture already armed")
        self.started.append((reason, window_s, max_requests))


class _StubRecorder:
    def slowest(self):
        return [{"request_id": "r-1", "request_ms": 99.0}]


class TestAlertActions:
    def _fire(self, eng, clock, feed=None, rec=None):
        feed = feed if feed is not None else [snap(gauge=10)]
        rec = rec or make_recorder(feed, clock)
        _step(feed, rec, eng, clock)
        eng.drain_actions()
        return feed, rec

    def test_capture_action_arms_workload(self, obs_on, tmp_path):
        clock = FakeClock()
        wl = _StubWorkload()
        eng = _engine([{"name": "hot", "type": "threshold",
                        "metric": "t_depth", "value": 5,
                        "actions": [{"do": "capture", "window_s": 8}]}],
                      clock, workload=wl)
        self._fire(eng, clock)
        assert wl.started == [("alert:hot", 8.0, None)]
        acts = [e for e in _events(eng, "action")
                if e["action"] == "capture"]
        assert acts and acts[0]["outcome"] == "ok" and acts[0]["on"] == "fire"

    def test_capture_requires_workload_at_boot(self):
        with pytest.raises(DataError, match="capture"):
            _engine([{"name": "h", "type": "threshold", "metric": "m",
                      "value": 1, "actions": [{"do": "capture"}]}],
                    FakeClock())

    def test_profile_requires_history_dir_at_boot(self):
        with pytest.raises(DataError, match="profile"):
            _engine([{"name": "h", "type": "threshold", "metric": "m",
                      "value": 1, "actions": [{"do": "profile"}]}],
                    FakeClock())

    def test_profile_action_writes_trace(self, obs_on, tmp_path,
                                         monkeypatch):
        from knn_tpu.obs import devprof

        monkeypatch.setattr(devprof, "capture_for",
                            lambda ms, **kw: {"traceEvents": [],
                                              "otherData": {"ms": ms}})
        clock = FakeClock()
        eng = _engine([{"name": "hot", "type": "threshold",
                        "metric": "t_depth", "value": 5,
                        "actions": [{"do": "profile", "ms": 50}]}],
                      clock, history_dir=str(tmp_path / "h"))
        self._fire(eng, clock)
        profiles = list((tmp_path / "h" / "profiles").glob("profile-hot-*.json"))
        assert len(profiles) == 1
        assert json.loads(profiles[0].read_text())["otherData"]["ms"] == 50

    def test_command_action_runs_on_fire_and_resolve(self, obs_on):
        clock = FakeClock()
        feed = [snap(gauge=10)]
        rec = make_recorder(feed, clock)
        eng = _engine([{"name": "hot", "type": "threshold",
                        "metric": "t_depth", "value": 5,
                        "actions": [{"do": "command",
                                     "cmd": f"{sys.executable} -c pass"}]}],
                      clock)
        _step(feed, rec, eng, clock)  # fire
        _step(feed, rec, eng, clock, gauge=1)  # resolve
        eng.drain_actions()
        acts = [e for e in _events(eng, "action")
                if e["action"] == "command"]
        assert [a["on"] for a in acts] == ["fire", "resolve"]
        assert all(a["outcome"] == "ok" for a in acts)
        # The contract appends event + alert name to the argv.
        assert acts[0]["detail"].endswith("fire hot")

    def test_failing_command_audited_as_error(self, obs_on):
        clock = FakeClock()
        eng = _engine([{"name": "hot", "type": "threshold",
                        "metric": "t_depth", "value": 5,
                        "actions": [{"do": "command", "cmd": "false"}]}],
                      clock)
        self._fire(eng, clock)
        acts = [e for e in _events(eng, "action")
                if e["action"] == "command"]
        assert acts and acts[0]["outcome"].startswith("error")

    def test_raising_action_audited_engine_survives(self, obs_on):
        clock = FakeClock()
        wl = _StubWorkload(raise_on_start=True)
        eng = _engine([{"name": "hot", "type": "threshold",
                        "metric": "t_depth", "value": 5,
                        "actions": [{"do": "capture"}]}],
                      clock, workload=wl)
        feed, rec = self._fire(eng, clock)
        acts = [e for e in _events(eng, "action")
                if e["action"] == "capture"]
        assert acts and acts[0]["outcome"].startswith("error")
        # The engine keeps evaluating: resolve still lands.
        _step(feed, rec, eng, clock, gauge=1)
        assert len(_events(eng, "resolve")) == 1

    def test_forensics_frozen_at_fire_time(self, obs_on, tmp_path):
        clock = FakeClock()
        eng = _engine([{"name": "hot", "type": "threshold",
                        "metric": "t_depth", "value": 5}],
                      clock, recorder=_StubRecorder(),
                      history_dir=str(tmp_path / "h"))
        self._fire(eng, clock)
        dumps = list((tmp_path / "h" / "forensics").glob("slowest-hot-*.json"))
        assert len(dumps) == 1
        doc = json.loads(dumps[0].read_text())
        assert doc["alert"] == "hot"
        assert doc["slowest"][0]["request_id"] == "r-1"

    def test_audit_file_written_line_buffered(self, obs_on, tmp_path):
        clock = FakeClock()
        eng = _engine([{"name": "hot", "type": "threshold",
                        "metric": "t_depth", "value": 5}],
                      clock, history_dir=str(tmp_path / "h"))
        feed, rec = self._fire(eng, clock)
        _step(feed, rec, eng, clock, gauge=1)
        eng.drain_actions()
        entries = [json.loads(ln) for ln in
                   (tmp_path / "h" / "alerts.jsonl")
                   .read_text().splitlines()]
        events = [e["event"] for e in entries]
        assert "fire" in events and "resolve" in events
        eng.close()


class TestRouterReplicaMerge:
    def test_scrape_tags_replica_label_and_merges(self, obs_on, tmp_path,
                                                  monkeypatch):
        from knn_tpu.fleet.router import RouterApp

        app = RouterApp(["http://127.0.0.1:9/"], health_interval_s=30.0,
                        history_dir=str(tmp_path / "rh"),
                        history_interval_s=5.0)
        try:
            monkeypatch.setattr(app.set, "usable_urls",
                                lambda: ["http://r1", "http://r2"])

            def fake_admin(method, url, payload, timeout=None):
                if url.startswith("http://r2"):
                    return None, None, "connection refused"
                assert url == "http://r1/metrics?format=json"
                return 200, {"snapshot": [
                    {"name": "knn_serve_requests_total", "kind": "counter",
                     "labels": {"kind": "predict"}, "help": "",
                     "value": 7.0}]}, None

            monkeypatch.setattr(app, "_admin_call", fake_admin)
            app.history.sample_once()
            app.history.sample_once()
            doc = app.history.query(metric="knn_serve_requests_total")
            series = doc["series"]
            # The member's scraped record carries its {replica} label —
            # raw per-replica values, never a pre-sum.
            assert len(series) == 1
            assert series[0]["labels"] == {"kind": "predict",
                                           "replica": "http://r1"}
            assert series[0]["points"][-1][1] == 7.0
            # The failed member is simply absent from this snapshot.
            assert not [s for s in series
                        if s["labels"].get("replica") == "http://r2"]
            # The router's OWN instruments land unlabelled-by-replica.
            own = app.history.query(metric="knn_history_snapshots_total")
            assert own["series"] and "replica" not in own["series"][0]["labels"]
        finally:
            app.close()


def _mini_problem():
    rng = np.random.default_rng(3)
    train_x = rng.integers(0, 4, (60, 4)).astype(np.float32)
    train_y = rng.integers(0, 3, 60).astype(np.int32)
    return Dataset(train_x, train_y)


def _http_get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestServeEndpoints:
    def test_debug_history_and_alerts_contracts(self, tmp_path, obs_on):
        from knn_tpu.serve.server import ServeApp, make_server

        model = KNNClassifier(k=3, engine="xla").fit(_mini_problem())
        rules = parse_rules([{"name": "hot", "type": "threshold",
                              "metric": "t_depth", "value": 5}])
        app = ServeApp(model, max_batch=8, max_wait_ms=0.5,
                       history_dir=str(tmp_path / "h"),
                       history_interval_s=60.0, alert_rules=rules)
        server = make_server(app)
        host, port = server.server_address[:2]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://{host}:{port}"
        try:
            app.history.sample_once()
            app.history.sample_once()  # the 2nd sees the 1st's counter
            st, doc = _http_get(base, "/debug/history"
                                      "?metric=knn_history_snapshots_total")
            assert st == 200 and doc["enabled"] is True
            assert doc["status"]["snapshots"] >= 1
            assert doc["series"][0]["name"] == "knn_history_snapshots_total"
            assert "index_version" in doc
            # Label + window filters, and their 400 contracts.
            st, doc = _http_get(base, "/debug/history?label=kind")
            assert st == 400 and "label" in doc["error"]
            st, doc = _http_get(base, "/debug/history?window=xyz")
            assert st == 400
            st, doc = _http_get(base, "/debug/history?window=5m")
            assert st == 200
            st, doc = _http_get(base, "/debug/alerts")
            assert st == 200 and doc["enabled"] is True
            assert doc["firing"] == []
            assert doc["rules"][0]["name"] == "hot"
            assert doc["rules"][0]["state"] == "ok"
            # /healthz carries both status blocks.
            st, h = _http_get(base, "/healthz")
            assert h["history"]["snapshots"] >= 1
            assert h["alerts"] == {"firing": [], "rules": 1}
        finally:
            server.shutdown()
            server.server_close()
            app.close()
        # close() takes one FINAL snapshot: the dir outlives the process.
        hist = load_history(str(tmp_path / "h"))
        assert hist.samples

    def test_disabled_is_absent_not_an_error(self, obs_on):
        from knn_tpu.serve.server import ServeApp, make_server

        model = KNNClassifier(k=3, engine="xla").fit(_mini_problem())
        app = ServeApp(model, max_batch=8, max_wait_ms=0.5)
        assert app.history is None and app.alerts is None
        server = make_server(app)
        host, port = server.server_address[:2]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://{host}:{port}"
        try:
            st, doc = _http_get(base, "/debug/history")
            assert st == 200 and doc["enabled"] is False
            assert doc["series"] == []
            st, doc = _http_get(base, "/debug/alerts")
            assert st == 200 and doc["enabled"] is False
            assert doc["rules"] == [] and doc["firing"] == []
            st, h = _http_get(base, "/healthz")
            assert h["history"] is None and h["alerts"] is None
        finally:
            server.shutdown()
            server.server_close()
            app.close()


class TestPostMortemCLI:
    def _crashed_dir(self, tmp_path):
        """A history dir as a SIGKILLed process leaves it: segments plus
        a torn half-written final line."""
        clock = FakeClock()
        feed = [snap(counter=0, gauge=1)]
        rec = make_recorder(feed, clock, str(tmp_path / "h"))
        for i in range(4):
            feed[0] = snap(counter=2 * i, gauge=1)
            rec.sample_once()
            clock.advance(1)
        with open(tmp_path / "h" / "seg-1.jsonl", "a",
                  encoding="utf-8") as f:
            f.write('{"t": 1004.0, "d": 1,')
        return str(tmp_path / "h")

    def test_history_cli_answers_from_crashed_dir(self, tmp_path, capsys):
        from knn_tpu.cli import run

        h = self._crashed_dir(tmp_path)
        assert run(["history", h, "--metric", "t_requests_total"]) == 0
        out = capsys.readouterr().out
        assert "torn tail repaired" in out
        assert "t_requests_total" in out
        # --json: machine-readable with the reconstruction metadata.
        assert run(["history", h, "--metric", "t_requests_total",
                    "--window", "2s", "--json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["samples"] == 4
        assert doc["repaired_torn_tail"] is False  # first run repaired it
        assert doc["series"][0]["points"][-1][1] == 6.0

    def test_history_cli_usage_errors_exit_2(self, tmp_path, capsys):
        from knn_tpu.cli import run

        h = self._crashed_dir(tmp_path)
        assert run(["history", str(tmp_path / "nope")]) == 2
        assert run(["history", h, "--window", "xyz"]) == 2
        assert run(["history", h, "--label", "novalue"]) == 2
        # Mid-file corruption is damage, not a crash signature: exit 2.
        seg = tmp_path / "h" / "seg-1.jsonl"
        lines = seg.read_text().splitlines()
        lines[2] = "garbage"
        seg.write_text("\n".join(lines) + "\n")
        assert run(["history", h]) == 2
        assert "Traceback" not in capsys.readouterr().err

    def test_report_cli_and_determinism(self, tmp_path, capsys):
        from knn_tpu.cli import run

        h = self._crashed_dir(tmp_path)
        (tmp_path / "h" / "alerts.jsonl").write_text(
            json.dumps({"ts": 1001.5, "alert": "hot", "event": "fire",
                        "severity": "page", "type": "threshold",
                        "value": 9.0, "actions": ["capture"]}) + "\n" +
            json.dumps({"ts": 1001.6, "alert": "hot", "event": "action",
                        "on": "fire", "action": "capture",
                        "outcome": "ok", "detail": "armed"}) + "\n" +
            json.dumps({"ts": 1003.0, "alert": "hot", "event": "resolve",
                        "severity": "page", "type": "threshold",
                        "value": 1.0}) + "\n")
        cap = tmp_path / "captures" / "workload-1001700"
        cap.mkdir(parents=True)
        (cap / "manifest.json").write_text(json.dumps(
            {"reason": "alert:hot", "t0_unix": 1001.7, "records": 5,
             "stop_reason": "window"}))
        access = tmp_path / "access.jsonl"
        access.write_text(
            json.dumps({"ts": 1001.2, "request_id": "r-9",
                        "kind": "predict", "status": 503,
                        "outcome": "overload", "ms": 1.0,
                        "rung": "fast"}) + "\n")

        load_history(h)  # settle the torn-tail repair first
        doc1 = build_report(h, access_log=str(access),
                            captures=str(tmp_path / "captures"))
        doc2 = build_report(h, access_log=str(access),
                            captures=str(tmp_path / "captures"))
        assert json.dumps(doc1, sort_keys=True) == \
            json.dumps(doc2, sort_keys=True)
        assert render_markdown(doc1) == render_markdown(doc2)

        kinds = [e["kind"] for e in doc1["timeline"]]
        assert {"alert-fire", "alert-resolve", "alert-action", "capture",
                "request-error"} <= set(kinds)
        # Chronological merge across sources.
        ts = [e["ts"] for e in doc1["timeline"]]
        assert ts == sorted(ts)
        assert doc1["alerts"] == {"fires": 1, "resolves": 1, "entries": 3}
        assert doc1["access_log"]["errors"] == 1
        counter_row = next(r for r in doc1["metrics"]
                           if r["name"] == "t_requests_total")
        assert counter_row["delta"] == 6.0

        out_md = tmp_path / "incident.md"
        out_json = tmp_path / "incident.json"
        assert run(["report", "--history", h,
                    "--access-log", str(access),
                    "--captures", str(tmp_path / "captures"),
                    "--out", str(out_md),
                    "--json-out", str(out_json)]) == 0
        md = out_md.read_text()
        assert "# Incident report" in md and "alert hot FIRED" in md
        assert json.loads(out_json.read_text())["alerts"]["fires"] == 1
        # A trailing window narrows the report.
        windowed = build_report(h, window=0.5)
        assert windowed["window"]["seconds"] == 0.5

    def test_report_cli_usage_errors_exit_2(self, tmp_path, capsys):
        from knn_tpu.cli import run

        assert run(["report", "--history", str(tmp_path / "nope")]) == 2
        h = self._crashed_dir(tmp_path)
        assert run(["report", "--history", h, "--window", "junk"]) == 2
        assert "Traceback" not in capsys.readouterr().err
