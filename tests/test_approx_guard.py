"""Sampled-recall guard for approx top-k (VERDICT r4 #7).

``--approx`` rides ``lax.approx_max_k``, whose recall target assumes the
true neighbors land at ~random positions. Regularly-strided structure
(e.g. tiled datasets) defeats its positional binning — recall measured
0.002 on the r4 33x-tiled set while the flag silently returned garbage.
``predict_arrays(approx=True)`` now scores a query sample against exact
top-k first and falls back to exact selection with a RuntimeWarning when
the measured recall misses the target.

On the CPU test platform ``approx_max_k`` lowers to exact top-k, so the
adversarial collapse cannot reproduce here; the guard trigger is pinned by
injecting the r4-measured recall, and the real-device behavior is
exercised by scripts/probe_approx_guard_r5.py (run on TPU).
"""

import warnings

import numpy as np
import pytest

from knn_tpu.backends import tpu as tpu_backend


def _tiled_problem(rng, base_n=300, reps=33, d=8, c=4, q=160):
    # q > _GUARD_SAMPLE (128): smaller query sets skip the guard entirely
    # and run exact (the sample would be the whole set — see
    # predict_arrays).
    base = rng.random((base_n, d), np.float32)
    train_x = np.tile(base, (reps, 1))
    train_x += 1e-3 * rng.standard_normal(train_x.shape, dtype=np.float32)
    train_y = np.tile(rng.integers(0, c, base_n).astype(np.int32), reps)
    test_x = base[rng.choice(base_n, q, replace=True)]
    return train_x, train_y, test_x, c


def test_guard_triggers_fallback_and_warns(rng, monkeypatch):
    train_x, train_y, test_x, c = _tiled_problem(rng)
    # Inject the r4 on-device measurement for this dataset shape (recall
    # 0.002 at recall_target=0.95): the guard must warn AND the predictions
    # must be the exact path's, not approx garbage.
    monkeypatch.setattr(
        tpu_backend, "sampled_approx_recall",
        lambda *a, **kw: 0.002,
    )
    want = tpu_backend.predict_arrays(
        train_x, train_y, test_x, 5, c, engine="xla",
    )
    with pytest.warns(RuntimeWarning, match="sampled recall 0.002"):
        got = tpu_backend.predict_arrays(
            train_x, train_y, test_x, 5, c, approx=True, engine="xla",
        )
    np.testing.assert_array_equal(got, want)


def test_guard_silent_when_recall_meets_target(rng):
    # CPU approx_max_k is exact -> measured recall 1.0 -> no warning, and
    # the approx path stays selected (identical predictions here since the
    # selection is exact on this platform).
    train_x, train_y, test_x, c = _tiled_problem(rng, reps=3)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        got = tpu_backend.predict_arrays(
            train_x, train_y, test_x, 5, c, approx=True, engine="xla",
        )
    want = tpu_backend.predict_arrays(
        train_x, train_y, test_x, 5, c, engine="xla",
    )
    np.testing.assert_array_equal(got, want)


def test_sampled_recall_math(rng):
    # On the exact-lowering CPU platform the sampled recall is 1.0 by
    # construction — pins the sampling/scoring arithmetic.
    train_x, train_y, test_x, _ = _tiled_problem(rng, reps=2)
    r = tpu_backend.sampled_approx_recall(train_x, test_x, 5, 0.95)
    assert r == 1.0


def test_small_query_sets_run_exact_without_guard(rng, monkeypatch):
    # q <= the guard sample: scoring would compute every query's exact
    # top-k and discard it, so approx is declined outright — exact
    # predictions, no warning, no guard invocation.
    called = []
    monkeypatch.setattr(
        tpu_backend, "sampled_approx_recall",
        lambda *a, **kw: called.append(1) or 1.0,
    )
    train_x, train_y, test_x, c = _tiled_problem(rng, reps=2, q=40)
    with warnings.catch_warnings():
        warnings.simplefilter("error", RuntimeWarning)
        got = tpu_backend.predict_arrays(
            train_x, train_y, test_x, 5, c, approx=True, engine="xla",
        )
    want = tpu_backend.predict_arrays(
        train_x, train_y, test_x, 5, c, engine="xla",
    )
    np.testing.assert_array_equal(got, want)
    assert not called


def test_guard_uses_resolved_metric(rng, monkeypatch):
    # approx + manhattan must score manhattan recall, not euclidean
    # (the guard exists to predict THIS call's approx fidelity).
    seen = []
    real = tpu_backend.sampled_approx_recall

    def spy(train_x, test_x, k, rt, precision="fast"):
        seen.append(precision)
        return real(train_x, test_x, k, rt, precision)

    monkeypatch.setattr(tpu_backend, "sampled_approx_recall", spy)
    train_x, train_y, test_x, c = _tiled_problem(rng, reps=2)
    tpu_backend.predict_arrays(
        train_x, train_y, test_x, 5, c, approx=True, engine="xla",
        metric="manhattan", precision="exact",
    )
    assert seen == ["manhattan"]


def test_guard_not_run_without_approx(rng, monkeypatch):
    # The guard costs a [sample, N] distance block; exact predicts must
    # not pay it.
    called = []
    monkeypatch.setattr(
        tpu_backend, "sampled_approx_recall",
        lambda *a, **kw: called.append(1) or 1.0,
    )
    train_x, train_y, test_x, c = _tiled_problem(rng, reps=2)
    tpu_backend.predict_arrays(train_x, train_y, test_x, 5, c, engine="xla")
    assert not called
