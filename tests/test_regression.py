"""KNNRegressor — the regression model family (a framework extension; the
reference casts the class column to int unconditionally, main.cpp:57, so it
cannot express this). Neighbor selection must be identical to the classifier's
(squared Euclidean, (distance, index) lexicographic order, SURVEY.md §3.5);
the reduction over neighbor targets is what's new.
"""

import numpy as np
import pytest

from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNRegressor


def _brute_neighbors(train_x, test_x, k):
    d = ((test_x[:, None, :] - train_x[None, :, :]) ** 2).sum(-1)
    n = train_x.shape[0]
    order = np.lexsort(
        (np.broadcast_to(np.arange(n), d.shape), d), axis=1
    )[:, :k]
    return np.take_along_axis(d, order, axis=1), order


def _make(rng, n=400, q=60, d=6):
    train_x = rng.integers(0, 5, (n, d)).astype(np.float32)
    targets = rng.normal(0, 10, n).astype(np.float32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, q // 2, replace=False)],
         rng.integers(0, 5, (q - q // 2, d)).astype(np.float32)]
    )
    # Deliberately negative int-cast labels: regression data routinely has
    # negative targets, and the regressor must never trip the classifier's
    # non-negative-label validation.
    train = Dataset(
        features=train_x,
        labels=targets.astype(np.int32),
        raw_targets=targets,
    )
    test = Dataset(
        features=test_x,
        labels=np.zeros(q, np.int32),
        raw_targets=rng.normal(0, 10, q).astype(np.float32),
    )
    return train, test


class TestKNNRegressor:
    @pytest.mark.parametrize("k", [1, 5])
    def test_uniform_matches_bruteforce(self, rng, k):
        train, test = _make(rng)
        model = KNNRegressor(k=k).fit(train)
        got = model.predict(test)
        _, order = _brute_neighbors(train.features, test.features, k)
        want = train.raw_targets[order].mean(axis=1)
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_distance_weighted(self, rng):
        train, test = _make(rng)
        k = 4
        model = KNNRegressor(k=k, weights="distance").fit(train)
        got = model.predict(test)
        dists, order = _brute_neighbors(train.features, test.features, k)
        want = np.empty(test.num_instances, np.float64)
        for i in range(test.num_instances):
            t = train.raw_targets[order[i]].astype(np.float64)
            if (dists[i] == 0).any():
                want[i] = t[dists[i] == 0].mean()
            else:
                w = 1.0 / dists[i]
                want[i] = (w * t).sum() / w.sum()
        np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-5)

    def test_exact_match_query_returns_exact_target(self, rng):
        # A query equal to exactly one train row: distance weighting must
        # return that row's target exactly, however close other rows are.
        train_x = np.array([[0.0, 0.0], [10.0, 10.0], [0.1, 0.0]], np.float32)
        targets = np.array([7.0, 100.0, -50.0], np.float32)
        train = Dataset(train_x, np.zeros(3, np.int32), raw_targets=targets)
        test = Dataset(train_x[:1], np.zeros(1, np.int32))
        got = KNNRegressor(k=2, weights="distance").fit(train).predict(test)
        np.testing.assert_allclose(got, [7.0])

    def test_tiny_nonzero_distances_stay_finite(self):
        # 1/d in float32 overflows to inf for d below ~3e-39, turning the
        # weighted mean into inf/inf = NaN; weights must be computed in f64.
        train = Dataset(
            np.array([[0.0], [1e-20], [1.0]], np.float32),
            np.zeros(3, np.int32),
            raw_targets=np.array([2.0, 4.0, 100.0], np.float32),
        )
        test = Dataset(np.array([[5e-21]], np.float32), np.zeros(1, np.int32))
        got = KNNRegressor(k=2, weights="distance").fit(train).predict(test)
        assert np.isfinite(got).all()
        assert 2.0 <= got[0] <= 4.0

    def test_nan_query_falls_back_to_uniform_mean(self):
        train = Dataset(
            np.array([[1.0], [2.0], [3.0]], np.float32),
            np.zeros(3, np.int32),
            raw_targets=np.array([1.0, 2.0, 9.0], np.float32),
        )
        test = Dataset(np.array([[np.nan]], np.float32), np.zeros(1, np.int32))
        got = KNNRegressor(k=2, weights="distance").fit(train).predict(test)
        # All distances +inf -> neighbors admitted in index order (0, 1).
        np.testing.assert_allclose(got, [(1.0 + 2.0) / 2])

    def test_score_is_r2(self, rng):
        train, test = _make(rng, n=200, q=30)
        model = KNNRegressor(k=3).fit(train)
        preds = model.predict(test)
        y = test.targets.astype(np.float64)
        want = 1 - ((y - preds) ** 2).sum() / ((y - y.mean()) ** 2).sum()
        assert model.score(test) == pytest.approx(want)
        # k=1 on a duplicate-free train set reproduces targets exactly.
        uniq = Dataset(
            np.arange(12, dtype=np.float32).reshape(6, 2),
            np.zeros(6, np.int32),
            raw_targets=np.linspace(-3, 3, 6).astype(np.float32),
        )
        assert KNNRegressor(k=1).fit(uniq).score(uniq) == pytest.approx(1.0)

    def test_validation_errors(self, rng):
        train, test = _make(rng, n=10, q=4)
        with pytest.raises(ValueError, match="k must be"):
            KNNRegressor(k=0)
        with pytest.raises(ValueError, match="weights"):
            KNNRegressor(k=1, weights="gaussian")
        with pytest.raises(ValueError, match="exceeds"):
            KNNRegressor(k=11).fit(train)
        bad = Dataset(np.zeros((4, 3), np.float32), np.zeros(4, np.int32))
        with pytest.raises(ValueError, match="features"):
            KNNRegressor(k=1).fit(train).predict(bad)
        with pytest.raises(RuntimeError, match="fit"):
            KNNRegressor(k=1).predict(test)


class TestRawTargets:
    def test_parsers_keep_uncast_targets(self, tmp_path):
        # 5.7 casts to label 5 (reference semantics) but the raw column
        # survives for regression — in BOTH parsers, identically.
        src = tmp_path / "t.arff"
        src.write_text(
            "@relation r\n"
            "@attribute x NUMERIC\n"
            "@attribute y NUMERIC\n"
            "@data\n"
            "1.0,5.7\n"
            "2.0,0.25\n"
            "3.0,3\n"
        )
        from knn_tpu.data import pyarff

        ds_py = pyarff.parse_arff_file(str(src))
        np.testing.assert_array_equal(ds_py.labels, [5, 0, 3])
        np.testing.assert_allclose(ds_py.raw_targets, [5.7, 0.25, 3.0], rtol=1e-6)

        try:
            from knn_tpu.native import arff_native
        except (ImportError, OSError):
            pytest.skip("native parser unavailable")
        ds_c = arff_native.parse(str(src))
        np.testing.assert_array_equal(ds_c.labels, ds_py.labels)
        np.testing.assert_array_equal(ds_c.raw_targets, ds_py.raw_targets)

    def test_write_arff_round_trips_float_targets(self, tmp_path):
        from knn_tpu.data.arff import write_arff, load_arff

        ds = Dataset(
            np.array([[1.0], [2.0]], np.float32),
            np.array([5, 0], np.int32),
            raw_targets=np.array([5.7, 0.25], np.float32),
        )
        out = tmp_path / "o.arff"
        write_arff(ds, str(out))
        back = load_arff(str(out))
        np.testing.assert_array_equal(back.labels, ds.labels)
        np.testing.assert_allclose(back.raw_targets, ds.raw_targets, rtol=1e-6)

    def test_targets_fallback_without_raw(self):
        ds = Dataset(np.zeros((2, 1), np.float32), np.array([3, 1], np.int32))
        np.testing.assert_array_equal(ds.targets, [3.0, 1.0])
        assert ds.targets.dtype == np.float32
