"""Serving subsystem contract tests (docs/SERVING.md).

The load-bearing claim is bit-identity: whatever batch a request's rows
were coalesced into, the batched path must return EXACTLY what the
synchronous per-request API returns — across concurrent client threads,
candidate engines, and both model families. Plus: coalescing measurably
happens (the ``knn_serve_batch_size`` histogram sees batches > 1 request),
admission control is typed (queue overflow → :class:`OverloadError` → 429,
deadlines → :class:`DeadlineExceededError` → 504), and the index artifact
round-trips to a model with identical predictions on every backend.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import AsyncResult, KNNClassifier, KNNRegressor
from knn_tpu.resilience.errors import (
    DataError, DeadlineExceededError, DeviceError, OverloadError,
)
from knn_tpu.serve.artifact import load_index, save_index, schema_hash, warmup
from knn_tpu.serve.batcher import MicroBatcher


def _problem(rng, n=300, q=40, d=5, c=5):
    train_x = rng.integers(0, 4, (n, d)).astype(np.float32)  # grid -> ties
    train_y = rng.integers(0, c, n).astype(np.int32)
    test_x = np.concatenate(
        [train_x[rng.choice(n, q // 2, replace=False)],
         rng.integers(0, 4, (q - q // 2, d)).astype(np.float32)]
    )
    train = Dataset(train_x, train_y)
    test = Dataset(test_x, np.zeros(len(test_x), np.int32))
    return train, test


@pytest.fixture
def obs_on():
    """Enabled + isolated observability for metric assertions."""
    was = obs.enabled()
    obs.enable()
    obs.reset()
    yield obs.registry()
    obs.reset()
    if not was:
        obs.disable()


class TestAsyncResultTimeout:
    def test_generic_finish_times_out_then_collects(self):
        release = threading.Event()

        def finish():
            release.wait(10)
            return 42

        h = AsyncResult(finish)
        with pytest.raises(DeadlineExceededError):
            h.result(timeout=0.02)
        release.set()
        assert h.result(timeout=5) == 42
        assert h.result() == 42  # memoized

    def test_generic_finish_error_is_memoized(self):
        def finish():
            raise DeviceError("boom")

        h = AsyncResult(finish)
        with pytest.raises(DeviceError):
            h.result(timeout=1)
        with pytest.raises(DeviceError):  # same outcome on re-resolve
            h.result(timeout=1)

    def test_timeout_aware_finish_gets_the_timeout(self):
        seen = []

        def finish(timeout=None):
            seen.append(timeout)
            return "v"

        finish.__accepts_timeout__ = True
        assert AsyncResult(finish).result(timeout=0.5) == "v"
        assert seen == [0.5]

    def test_no_timeout_path_unchanged(self):
        h = AsyncResult(lambda: 7)
        assert h.result() == 7


def _models(train, reg_train):
    return [
        ("clf-uniform", KNNClassifier(k=5, engine="xla").fit(train)),
        ("clf-stripe", KNNClassifier(k=5, engine="stripe").fit(train)),
        ("clf-auto", KNNClassifier(k=5).fit(train)),
        ("clf-weighted", KNNClassifier(k=5, weights="distance").fit(train)),
        ("reg-uniform", KNNRegressor(k=5, engine="xla").fit(reg_train)),
        ("reg-weighted", KNNRegressor(k=5, weights="distance").fit(reg_train)),
    ]


class TestBatcherBitIdentity:
    def test_concurrent_clients_match_sync(self, rng):
        """The acceptance criterion: every request's batched result equals
        the synchronous API on the same rows — threads × engines × both
        model families, mixed predict/kneighbors kinds, varying row
        counts, whatever batches the coalescer happened to form."""
        train, test = _problem(rng)
        reg_train = Dataset(
            train.features, train.labels,
            raw_targets=rng.standard_normal(
                train.num_instances).astype(np.float32),
        )
        for name, model in _models(train, reg_train):
            requests = []
            for i in range(24):
                lo = (3 * i) % (test.num_instances - 3)
                rows = test.features[lo:lo + 1 + (i % 3)]
                requests.append((rows, "kneighbors" if i % 4 == 3
                                 else "predict"))
            sync = []
            for rows, kind in requests:
                ds = Dataset(rows, np.zeros(len(rows), np.int32))
                sync.append(model.kneighbors(ds) if kind == "kneighbors"
                            else model.predict(ds))

            with MicroBatcher(model, max_batch=16, max_wait_ms=20.0) as b:
                results = [None] * len(requests)
                errors = []

                def client(ix):
                    try:
                        rows, kind = requests[ix]
                        results[ix] = b.submit(rows, kind).result(timeout=60)
                    except Exception as e:  # noqa: BLE001 — surfaced below
                        errors.append((ix, e))

                threads = [threading.Thread(target=client, args=(ix,))
                           for ix in range(len(requests))]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
            assert not errors, f"{name}: {errors}"
            for ix, ((rows, kind), want, got) in enumerate(
                    zip(requests, sync, results)):
                if kind == "kneighbors":
                    np.testing.assert_array_equal(
                        got[0], want[0], err_msg=f"{name} req {ix} dists")
                    np.testing.assert_array_equal(
                        got[1], want[1], err_msg=f"{name} req {ix} indices")
                else:
                    np.testing.assert_array_equal(
                        got, want, err_msg=f"{name} req {ix} predictions")

    def test_single_row_convenience_roundtrip(self, rng):
        train, test = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        want = model.predict(test)
        with MicroBatcher(model, max_batch=8, max_wait_ms=1.0) as b:
            got = np.concatenate(
                [b.predict(test.features[i], timeout=60)
                 for i in range(test.num_instances)]
            )
        np.testing.assert_array_equal(got, want)


class TestBatcherPolicy:
    def test_coalescing_actually_happens(self, rng, obs_on):
        """knn_serve_batch_size must record batches of >1 request when
        concurrent clients overlap a generous wait window — dynamic
        batching measurably engaging, not just configured."""
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        model.kneighbors(test)  # warm the executable outside the window
        with MicroBatcher(model, max_batch=32, max_wait_ms=250.0) as b:
            handles = [b.submit(test.features[i]) for i in range(8)]
            for h in handles:
                h.result(timeout=60)
        hist = obs_on.histogram("knn_serve_batch_size")
        assert hist.count >= 1
        assert hist.sum > hist.count, (
            f"every batch held a single request (batches={hist.count}, "
            f"requests={hist.sum}) — coalescing never engaged"
        )

    def test_queue_overflow_typed_and_counted(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        b = MicroBatcher(model, max_batch=2, max_queue_rows=2,
                         max_wait_ms=2000.0)
        try:
            first = b.submit(test.features[0])
            with pytest.raises(OverloadError, match="queue full"):
                b.submit(test.features[:2])  # 1 queued + 2 > bound
            second = b.submit(test.features[1])  # fills the batch: dispatch
            assert first.result(timeout=60) is not None
            assert second.result(timeout=60) is not None
        finally:
            b.close()
        assert obs_on.counter("knn_serve_rejected_total",
                              reason="queue_full").value == 1

    def test_deadline_expires_in_queue(self, rng, obs_on):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        with MicroBatcher(model, max_batch=64, max_wait_ms=120.0) as b:
            h = b.submit(test.features[0], deadline_ms=5)
            with pytest.raises(DeadlineExceededError, match="expired"):
                h.result(timeout=60)
        assert obs_on.counter("knn_serve_deadline_expired_total").value == 1

    def test_result_timeout_then_collect(self, rng):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        want = model.predict(test)
        with MicroBatcher(model, max_batch=64, max_wait_ms=300.0) as b:
            h = b.submit(test.features)
            with pytest.raises(DeadlineExceededError):
                h.result(timeout=0.01)  # batch window still open
            np.testing.assert_array_equal(h.result(timeout=60), want)

    def test_close_drains_then_rejects(self, rng):
        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        b = MicroBatcher(model, max_batch=64, max_wait_ms=500.0)
        handles = [b.submit(test.features[i]) for i in range(4)]
        b.close()  # cuts the wait window short and drains
        for h in handles:
            assert h.result(timeout=60) is not None
        with pytest.raises(OverloadError, match="shut down"):
            b.submit(test.features[0])

    def test_every_rung_failing_delivers_typed_error(self, rng, monkeypatch):
        """A fast-rung failure DEGRADES now (TestServingLadder pins that);
        the typed error reaches the futures only when the whole serving
        ladder is exhausted."""
        import knn_tpu.backends.oracle as oracle_mod
        import knn_tpu.serve.batcher as batcher_mod

        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)

        def boom(*args, **kwargs):
            raise DeviceError("synthetic dispatch failure")

        monkeypatch.setattr(model, "kneighbors", boom)
        monkeypatch.setattr(batcher_mod, "_kneighbors_arrays", boom)
        monkeypatch.setattr(oracle_mod, "oracle_kneighbors", boom)
        with MicroBatcher(model, max_batch=8, max_wait_ms=1.0) as b:
            h1 = b.submit(test.features[0])
            h2 = b.submit(test.features[1], "kneighbors")
            for h in (h1, h2):
                with pytest.raises(DeviceError, match="synthetic"):
                    h.result(timeout=60)

    def test_worker_survives_instrumentation_failure(self, rng, obs_on,
                                                     monkeypatch):
        """An exception OUTSIDE the dispatch try (e.g. a metric-ladder
        conflict in the recording helpers) must neither strand the batch's
        futures nor kill the worker thread — a dead worker presents as a
        hung server (found live: bench_serving registered
        knn_serve_batch_size with conflicting buckets)."""
        from knn_tpu.obs import instrument

        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)

        def broken(ms, kind):
            raise ValueError("synthetic instrumentation bug")

        monkeypatch.setattr(instrument, "record_serve_queue_wait", broken)
        with MicroBatcher(model, max_batch=8, max_wait_ms=1.0) as b:
            with pytest.raises(ValueError, match="instrumentation"):
                b.submit(test.features[0]).result(timeout=60)
            monkeypatch.undo()
            # The worker is still alive and serving.
            assert b.predict(test.features[0], timeout=60) is not None

    def test_shape_and_kind_rejected_at_submit(self, rng):
        train, test = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        with MicroBatcher(model, max_wait_ms=0.0) as b:
            with pytest.raises(ValueError, match="features must be"):
                b.submit(test.features[:, :2])
            with pytest.raises(ValueError, match="kind"):
                b.submit(test.features[0], "explain")
            with pytest.raises(ValueError, match="empty"):
                b.submit(test.features[:0])

    def test_unfitted_model_rejected_at_build(self):
        with pytest.raises(RuntimeError, match="fit"):
            MicroBatcher(KNNClassifier(k=3))

    def test_bad_policy_rejected(self, rng):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        with pytest.raises(ValueError, match="max_batch"):
            MicroBatcher(model, max_batch=0)
        with pytest.raises(ValueError, match="max_queue_rows"):
            MicroBatcher(model, max_batch=64, max_queue_rows=8)


class TestArtifact:
    def test_round_trip_every_backend(self, rng, tmp_path):
        """The artifact must reconstruct a model whose predictions are
        bit-identical to the saved one — for every registered backend."""
        from knn_tpu.backends import available_backends

        train, test = _problem(rng)
        for ix, backend in enumerate(available_backends()):
            model = KNNClassifier(k=3, backend=backend).fit(train)
            want = model.predict(test)
            out = save_index(model, tmp_path / f"idx{ix}")
            loaded = load_index(out)
            assert loaded.backend_name == backend
            assert loaded.k == 3
            np.testing.assert_array_equal(
                loaded.predict(test), want, err_msg=backend)

    def test_regressor_round_trip_with_raw_targets(self, rng, tmp_path):
        train, test = _problem(rng)
        reg_train = Dataset(
            train.features, train.labels,
            raw_targets=rng.standard_normal(
                train.num_instances).astype(np.float32),
        )
        model = KNNRegressor(k=4, weights="distance").fit(reg_train)
        want = model.predict(test)
        loaded = load_index(save_index(model, tmp_path / "reg"))
        assert isinstance(loaded, KNNRegressor)
        assert loaded.weights == "distance"
        np.testing.assert_array_equal(loaded.predict(test), want)
        np.testing.assert_array_equal(
            loaded.train_.raw_targets, reg_train.raw_targets)

    def test_manifest_fields(self, rng, tmp_path):
        train, _ = _problem(rng)
        out = save_index(KNNClassifier(k=5).fit(train), tmp_path / "m")
        manifest = json.loads((out / "manifest.json").read_text())
        assert manifest["format"] == 3
        assert manifest["family"] == "classifier"
        assert manifest["k"] == 5
        assert manifest["metric"] == "euclidean"
        assert manifest["dtype"] == "float32"
        assert manifest["train_rows"] == train.num_instances
        assert manifest["num_features"] == train.num_features
        assert manifest["num_classes"] == train.num_classes
        assert manifest["schema_hash"] == schema_hash(train)
        # Format 2: the training-distribution sketch for drift detection
        # (obs/drift.py) rides the manifest.
        sketch = manifest["drift_sketch"]
        assert sketch["count"] == train.num_instances
        assert sketch["num_features"] == train.num_features
        assert len(sketch["mean"]) == train.num_features
        np.testing.assert_allclose(
            np.asarray(sketch["mean"]),
            train.features.astype(np.float64).mean(axis=0), atol=1e-6)

    def test_pre_sketch_artifact_loads_and_reports_no_baseline(
            self, rng, tmp_path):
        """The format-bump back-compat guard: a format-1 (sketch-less)
        artifact round-trips cleanly — identical predictions — and drift
        reports the DISTINCT no-baseline state, never fabricated
        scores."""
        from knn_tpu.obs.drift import DriftMonitor
        from knn_tpu.serve.artifact import read_manifest, reference_sketch

        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        want = model.predict(test)
        out = save_index(model, tmp_path / "v1")
        # Rewrite the manifest as a format-1 artifact (what a pre-PR-7
        # save-index produced): no drift_sketch, format 1.
        mf = out / "manifest.json"
        doc = json.loads(mf.read_text())
        doc["format"] = 1
        del doc["drift_sketch"]
        mf.write_text(json.dumps(doc))
        loaded = load_index(out)  # loads cleanly, no error
        np.testing.assert_array_equal(loaded.predict(test), want)
        manifest = read_manifest(out)
        assert reference_sketch(manifest) is None
        m = DriftMonitor(reference_sketch(manifest), rate=1.0,
                         num_features=train.num_features, autostart=False)
        m.offer(test.features[:4])
        summary = m.export()
        m.close()
        assert summary["baseline"] == "absent"
        assert summary["scores"] is None

    def test_format_matrix_serves_with_mutable_absent(self, rng, tmp_path):
        """The artifact back-compat matrix under the mutable tier: format
        1 (pre-sketch), format 2 (pre-IVF), and format 3 (exact AND
        partitioned) all load, serve identical answers through a default
        (immutable) ServeApp, and report the DISTINCT ``mutable: absent``
        state — None in /healthz, no fabricated freshness numbers, zero
        ``knn_mutable_*`` instruments."""
        from knn_tpu.index.ivf import IVFIndex
        from knn_tpu.serve.server import ServeApp

        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        want = model.predict(test)

        def downgrade(out, fmt):
            mf = out / "manifest.json"
            doc = json.loads(mf.read_text())
            doc["format"] = fmt
            if fmt < 2:
                doc.pop("drift_sketch", None)
            mf.write_text(json.dumps(doc))
            return out

        ivf = IVFIndex.build(train.features, 8, seed=0)
        cases = {
            "format1": downgrade(save_index(model, tmp_path / "f1"), 1),
            "format2": downgrade(save_index(model, tmp_path / "f2"), 2),
            "format3": save_index(model, tmp_path / "f3"),
            "format3-ivf": save_index(model, tmp_path / "f3i", ivf=ivf),
        }
        for name, out in cases.items():
            loaded = load_index(out)
            np.testing.assert_array_equal(loaded.predict(test), want,
                                          err_msg=name)
            app = ServeApp(loaded, max_batch=8, max_wait_ms=0.0,
                           **({"ivf_probes": 8}
                              if name == "format3-ivf" else {}))
            try:
                app.warm((1,))
                health = app.health()
                assert health["mutable"] is None, name
                got = app.batcher.submit(
                    test.features[:4], "predict").result(60)
                np.testing.assert_array_equal(got, want[:4], err_msg=name)
                assert app.mutable is None and app.compactor is None, name
                assert app.batcher.mutable is None, name
            finally:
                app.close()
        assert not any(i.name.startswith("knn_mutable_")
                       for i in obs.registry().instruments())

    def test_missing_artifact_typed(self, tmp_path):
        with pytest.raises(DataError, match="not found"):
            load_index(tmp_path / "nope")

    def test_not_an_artifact_typed(self, tmp_path):
        plain = tmp_path / "plain"
        plain.mkdir()
        (plain / "junk.txt").write_text("x")
        with pytest.raises(DataError, match="not an index artifact"):
            load_index(plain)

    def test_newer_format_rejected(self, rng, tmp_path):
        train, _ = _problem(rng)
        out = save_index(KNNClassifier(k=3).fit(train), tmp_path / "v")
        mf = out / "manifest.json"
        doc = json.loads(mf.read_text())
        doc["format"] = 999
        mf.write_text(json.dumps(doc))
        with pytest.raises(DataError, match="newer"):
            load_index(out)

    def test_schema_hash_mismatch_rejected(self, rng, tmp_path):
        train, _ = _problem(rng)
        out = save_index(KNNClassifier(k=3).fit(train), tmp_path / "h")
        mf = out / "manifest.json"
        doc = json.loads(mf.read_text())
        doc["schema_hash"] = "0" * 32
        mf.write_text(json.dumps(doc))
        with pytest.raises(DataError, match="schema hash mismatch"):
            load_index(out)

    def test_corrupt_arrays_typed(self, rng, tmp_path):
        # BadZipFile is not OSError/ValueError; a truncated arrays.npz
        # must still land in DataError (exit 2 from the CLI), never a
        # traceback.
        train, _ = _problem(rng)
        out = save_index(KNNClassifier(k=3).fit(train), tmp_path / "c")
        (out / "arrays.npz").write_bytes(b"definitely not a zip archive")
        with pytest.raises(DataError, match="unreadable arrays"):
            load_index(out)

    def test_refuses_to_clobber_foreign_dir(self, rng, tmp_path):
        train, _ = _problem(rng)
        victim = tmp_path / "precious"
        victim.mkdir()
        (victim / "thesis.txt").write_text("irreplaceable")
        with pytest.raises(ValueError, match="refusing"):
            save_index(KNNClassifier(k=3).fit(train), victim)
        assert (victim / "thesis.txt").read_text() == "irreplaceable"

    def test_resave_over_artifact_allowed(self, rng, tmp_path):
        train, test = _problem(rng)
        out = save_index(KNNClassifier(k=3).fit(train), tmp_path / "re")
        save_index(KNNClassifier(k=5).fit(train), out)
        assert load_index(out).k == 5

    def test_warmup_reports_per_shape_wall(self, rng):
        train, _ = _problem(rng)
        model = KNNClassifier(k=3).fit(train)
        out = warmup(model, batch_sizes=(1, 4), kinds=("predict",
                                                       "kneighbors"))
        assert set(out) == {"predict@1", "predict@4", "kneighbors@1",
                            "kneighbors@4"}
        assert all(ms >= 0 for ms in out.values())
        with pytest.raises(ValueError, match=">= 1"):
            warmup(model, batch_sizes=(0,))
        with pytest.raises(ValueError, match="kind"):
            warmup(model, kinds=("segment",))


# ---------------------------------------------------------------------------
# HTTP front-end


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture
def served(rng, obs_on):
    """A warmed in-process server on an ephemeral port."""
    from knn_tpu.serve.server import ServeApp, make_server

    train, test = _problem(rng)
    model = KNNClassifier(k=3, engine="xla").fit(train)
    app = ServeApp(model, max_batch=16, max_wait_ms=1.0)
    server = make_server(app)
    host, port = server.server_address[:2]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    app.warm((1, 4))
    try:
        yield f"http://{host}:{port}", model, test, app
    finally:
        server.shutdown()
        server.server_close()
        app.close()
        thread.join(timeout=10)


class TestServer:
    def test_healthz_gates_on_warmup(self, rng, obs_on):
        from knn_tpu.serve.server import ServeApp, make_server

        train, _ = _problem(rng)
        app = ServeApp(KNNClassifier(k=3, engine="xla").fit(train))
        server = make_server(app)
        host, port = server.server_address[:2]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        base = f"http://{host}:{port}"
        try:
            st, body = _get(base, "/healthz")
            assert st == 503 and not json.loads(body)["ready"]
            app.warm((1,))
            st, body = _get(base, "/healthz")
            health = json.loads(body)
            assert st == 200 and health["ready"]
            assert health["warmup_ms"]  # the compile happened pre-ready
        finally:
            server.shutdown()
            server.server_close()
            app.close()

    def test_predict_matches_sync(self, served):
        base, model, test, _ = served
        want = model.predict(test).tolist()
        st, body = _post(base, "/predict", {"instances":
                                            test.features.tolist()})
        assert st == 200
        assert body["predictions"] == want

    def test_kneighbors_endpoint(self, served):
        base, model, test, _ = served
        want_d, want_i = model.kneighbors(
            Dataset(test.features[:3], np.zeros(3, np.int32)))
        st, body = _post(base, "/kneighbors",
                         {"instances": test.features[:3].tolist()})
        assert st == 200
        np.testing.assert_array_equal(np.asarray(body["indices"]), want_i)
        np.testing.assert_allclose(np.asarray(body["distances"]), want_d)

    def test_metrics_exposition(self, served):
        base, _, test, _ = served
        _post(base, "/predict", {"instances": test.features[:2].tolist()})
        st, text = _get(base, "/metrics")
        assert st == 200
        for needle in ("knn_serve_requests_total", "knn_serve_batch_size",
                       "knn_serve_request_ms"):
            assert needle in text, needle

    def test_deadline_maps_to_504(self, rng, obs_on):
        from knn_tpu.serve.server import ServeApp, make_server

        train, test = _problem(rng)
        # A wait window far past the deadline: the request cannot be served
        # in time by construction.
        app = ServeApp(KNNClassifier(k=3, engine="xla").fit(train),
                       max_batch=64, max_wait_ms=2000.0)
        server = make_server(app)
        host, port = server.server_address[:2]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://{host}:{port}"
        try:
            app.warm((1,))
            st, body = _post(base, "/predict", {
                "instances": [test.features[0].tolist()], "deadline_ms": 20,
            })
            assert st == 504
            assert "error" in body
        finally:
            server.shutdown()
            server.server_close()
            app.close()

    def test_overflow_maps_to_429(self, rng, obs_on):
        from knn_tpu.serve.server import ServeApp, make_server

        train, test = _problem(rng)
        # A LONG coalesce window: the parked row must still be queued
        # when the overflow probe lands, even on a contended CI box (the
        # filler request below closes the batch early at max_batch, so
        # the test never actually waits the window out).
        app = ServeApp(KNNClassifier(k=3, engine="xla").fit(train),
                       max_batch=2, max_queue_rows=2, max_wait_ms=20000.0)
        server = make_server(app)
        host, port = server.server_address[:2]
        threading.Thread(target=server.serve_forever, daemon=True).start()
        base = f"http://{host}:{port}"
        try:
            app.warm((1,))
            # One row parks in the 2 s coalesce window; a 2-row request on
            # top exceeds the queue bound deterministically.
            first = {}

            def park():
                first["resp"] = _post(base, "/predict", {
                    "instances": [test.features[0].tolist()]})

            t = threading.Thread(target=park)
            t.start()
            # Wait for the parked row to actually be QUEUED: if the 2-row
            # probe wins admission first, the PARK request is the one
            # rejected (2+1 > bound) and no later probe can overflow an
            # empty queue — the race this test flaked on under load.
            parked_by = time.monotonic() + 10
            while (time.monotonic() < parked_by
                   and app.batcher.pending_rows() == 0):
                time.sleep(0.005)
            assert app.batcher.pending_rows() == 1
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                st, body = _post(base, "/predict", {
                    "instances": test.features[1:3].tolist()})
                if st == 429:
                    break
                time.sleep(0.01)
            assert st == 429, (st, body)
            assert "error" in body
            # Close the parked batch NOW (1+1 rows = max_batch) instead
            # of riding out the coalesce window.
            st_fill, _ = _post(base, "/predict", {
                "instances": [test.features[3].tolist()]})
            assert st_fill == 200
            t.join(timeout=30)
            assert first["resp"][0] == 200  # the parked request still served
        finally:
            server.shutdown()
            server.server_close()
            app.close()

    def test_malformed_requests_400(self, served):
        base, _, test, _ = served
        st, body = _post(base, "/predict", {"rows": [[1.0]]})
        assert st == 400
        st, body = _post(base, "/predict", {"instances": [[1.0, 2.0]]})
        assert st == 400
        st, body = _post(base, "/predict",
                         {"instances": test.features[:1].tolist(),
                          "deadline_ms": -5})
        assert st == 400
        # JSON "Infinity" parses to float inf; it must be a 400, not an
        # OverflowError traceback in the handler thread.
        st, body = _post(base, "/predict",
                         {"instances": test.features[:1].tolist(),
                          "deadline_ms": 1e400})
        assert st == 400 and "finite" in body["error"]
        req = urllib.request.Request(
            base + "/predict", data=b"{not json", method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 400

    def test_unknown_endpoint_404(self, served):
        base = served[0]
        assert _get(base, "/explain")[0] == 404
        st, _ = _post(base, "/train", {"instances": []})
        assert st == 404


class TestServerQuality:
    """The quality surfaces (docs/OBSERVABILITY.md §Quality & drift):
    /debug/quality, the /healthz quality block, and the knn_quality_*/
    knn_drift_* scrape rows — plus the disabled shape (rate 0 builds
    NOTHING)."""

    @pytest.fixture
    def served_quality(self, rng, obs_on, tmp_path):
        """A warmed server with shadow scoring + drift on at rate 1 and a
        real training-sketch baseline."""
        from knn_tpu.obs.drift import StreamSketch
        from knn_tpu.serve.server import ServeApp, make_server

        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        app = ServeApp(
            model, max_batch=16, max_wait_ms=1.0,
            shadow_rate=1.0, drift_rate=1.0, quality_queue=1024,
            reference_sketch=StreamSketch.from_data(
                train.features).to_dict(),
        )
        server = make_server(app)
        host, port = server.server_address[:2]
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        app.warm((1, 4))
        try:
            yield f"http://{host}:{port}", model, test, app
        finally:
            server.shutdown()
            server.server_close()
            app.close()
            thread.join(timeout=10)

    def test_debug_quality_joins_recall_drift_and_burn(self,
                                                       served_quality):
        base, model, test, app = served_quality
        st, _ = _post(base, "/predict",
                      {"instances": test.features[:8].tolist()})
        assert st == 200
        assert app.quality.drain(30) and app.drift.drain(30)
        st, body = _get(base, "/debug/quality")
        assert st == 200
        doc = json.loads(body)
        assert doc["enabled"] == {"shadow": True, "drift": True}
        fast = doc["shadow"]["rungs"]["fast"]
        assert fast["recall"] == 1.0 and fast["divergence"] == {}
        assert doc["drift"]["baseline"] == "present"
        assert doc["drift"]["scores"] is not None
        assert "burn_rates" in doc["slo_quality"]

    def test_healthz_quality_block_and_metrics(self, served_quality):
        base, _, test, app = served_quality
        _post(base, "/predict", {"instances": test.features[:4].tolist()})
        assert app.quality.drain(30)
        st, body = _get(base, "/healthz")
        h = json.loads(body)
        assert st == 200
        assert h["quality"]["shadow"]["scored"] >= 1
        assert h["quality"]["drift"]["baseline"] == "present"
        st, text = _get(base, "/metrics")
        assert st == 200
        for name in ("knn_quality_recall", "knn_quality_scored_total",
                     "knn_drift_baseline_present",
                     'knn_slo_burn_rate{objective="quality"'):
            assert name in text, name

    def test_disabled_layers_report_null_not_404(self, served):
        """Rate 0 (the default) constructs nothing; /debug/quality stays
        routable for dashboards and says so."""
        base, _, _, app = served
        assert app.quality is None and app.drift is None
        st, body = _get(base, "/debug/quality")
        assert st == 200
        doc = json.loads(body)
        assert doc["enabled"] == {"shadow": False, "drift": False}
        assert doc["shadow"] is None and doc["drift"] is None
        st, body = _get(base, "/healthz")
        q = json.loads(body)["quality"]
        assert q == {"shadow": None, "drift": None}

    def test_shadow_on_keeps_responses_bit_identical(self, served_quality):
        base, model, test, _ = served_quality
        want = model.predict(test).tolist()
        st, body = _post(base, "/predict",
                         {"instances": test.features.tolist()})
        assert st == 200 and body["predictions"] == want


class TestDrainOrdering:
    def test_listener_refuses_before_healthz_flips(self, rng, obs_on):
        """The SIGTERM sequence (``drain_and_stop``): the LISTENING
        socket must already refuse new connections at the instant the
        app flips to draining — so a fleet router's connection-refused
        demotion fires immediately, and no connection can ever be
        accepted into the 503 window and die untracked. An in-flight
        request admitted before the drain still completes 200 (its
        connection socket is not the listener)."""
        import socket

        from knn_tpu.serve.server import (
            ServeApp,
            drain_and_stop,
            make_server,
        )

        train, test = _problem(rng)
        model = KNNClassifier(k=3, engine="xla").fit(train)
        model.predict(test)  # pre-compile: the in-flight leg times a
        # dispatch, not a first-call compile
        app = ServeApp(model, max_batch=8, max_wait_ms=300.0)
        server = make_server(app)
        host, port = server.server_address[:2]
        threading.Thread(target=server.serve_forever,
                         daemon=True).start()
        app.ready = True

        probe = {}
        orig_drain = app.drain

        def probing_drain(timeout_s):
            # This runs at the exact moment the old code would have
            # flipped healthz FIRST: the listener must already be gone.
            try:
                socket.create_connection((host, port), timeout=2).close()
                probe["refused"] = False
            except ConnectionRefusedError:
                probe["refused"] = True
            except OSError as e:
                probe["refused"] = f"unexpected {type(e).__name__}: {e}"
            return orig_drain(timeout_s)

        app.drain = probing_drain
        results = []

        def client():
            req = urllib.request.Request(
                f"http://{host}:{port}/predict",
                data=json.dumps(
                    {"instances": [test.features[0].tolist()]}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                results.append(r.status)

        t = threading.Thread(target=client, daemon=True)
        t.start()
        time.sleep(0.1)  # the request is admitted, parked in the
        # batcher's 300 ms coalescing window — in flight across the
        # drain
        try:
            summary = drain_and_stop(server, drain_timeout_s=10.0)
            t.join(timeout=15)
            assert probe["refused"] is True
            assert results == [200]
            assert summary["drained_clean"] is True
            assert summary["inflight_at_exit"] == 0
        finally:
            server.server_close()
            app.close()
