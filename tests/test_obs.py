"""Observability subsystem tests: span nesting/ordering, histogram bucket
edges, Perfetto trace schema, Prometheus text exposition, the CLI artifact
round-trip, and the live collective-traffic counters' exact agreement with
``parallel/comm_audit.py``'s analytic byte model."""

import io
import json
import math
import threading

import numpy as np
import pytest

from knn_tpu import obs
from knn_tpu.obs.metrics import Histogram, MetricsRegistry
from knn_tpu.obs.tracer import SpanTracer


@pytest.fixture()
def global_obs():
    """Enable the global tracer/registry for one test, restoring the
    disabled default (and empty state) afterwards."""
    obs.reset()
    obs.enable()
    yield obs
    obs.disable()
    obs.reset()


class TestSpanTracer:
    def test_nesting_parent_depth(self):
        tr = SpanTracer()
        with tr.span("outer"):
            with tr.span("mid"):
                with tr.span("inner"):
                    pass
            with tr.span("mid2"):
                pass
        spans = {s.name: s for s in tr.spans()}
        assert spans["outer"].parent is None and spans["outer"].depth == 0
        assert spans["mid"].parent is spans["outer"]
        assert spans["inner"].parent is spans["mid"]
        assert spans["inner"].depth == 2
        assert spans["mid2"].parent is spans["outer"]

    def test_completion_order_children_first(self):
        tr = SpanTracer()
        with tr.span("a"):
            with tr.span("b"):
                pass
        assert [s.name for s in tr.spans()] == ["b", "a"]

    def test_durations_nested_within_parent(self):
        tr = SpanTracer()
        with tr.span("outer"):
            with tr.span("inner"):
                pass
        spans = {s.name: s for s in tr.spans()}
        assert 0 <= spans["inner"].dur_ns <= spans["outer"].dur_ns
        assert spans["inner"].start_ns >= spans["outer"].start_ns

    def test_aggregate_by_name_and_by_parent(self):
        tr = SpanTracer()
        with tr.span("region") as region:
            with tr.span("x"):
                pass
            with tr.span("x"):
                with tr.span("y"):
                    pass
        agg = tr.aggregate()
        assert agg["x"]["count"] == 2 and agg["y"]["count"] == 1
        children = tr.aggregate(parent=region)
        assert set(children) == {"x"}  # y is a grandchild
        assert children["x"]["count"] == 2

    def test_threads_nest_independently(self):
        tr = SpanTracer()
        done = threading.Event()

        def worker():
            with tr.span("worker_root"):
                with tr.span("worker_child"):
                    done.wait(5)

        t = threading.Thread(target=worker)
        with tr.span("main_root"):
            t.start()
            done.set()
            t.join()
        spans = {s.name: s for s in tr.spans()}
        assert spans["worker_root"].parent is None
        assert spans["worker_child"].parent is spans["worker_root"]
        assert spans["worker_root"].tid != spans["main_root"].tid

    def test_buffer_cap_counts_drops(self):
        tr = SpanTracer(max_spans=2)
        for _ in range(4):
            with tr.span("s"):
                pass
        assert len(tr.spans()) == 2 and tr.dropped == 2
        assert tr.to_chrome_trace()["otherData"]["spans_dropped"] == 2
        tr.reset()
        assert tr.dropped == 0

    def test_attrs_survive_to_trace_args(self):
        tr = SpanTracer()
        with tr.span("s", backend="tpu", k=5):
            pass
        [b, _] = tr.trace_events()
        assert b["args"] == {"backend": "tpu", "k": 5}


class TestPerfettoExport:
    def _check_trace(self, doc):
        assert isinstance(doc["traceEvents"], list)
        stack = []
        last_ts = -math.inf
        for e in doc["traceEvents"]:
            assert e["ph"] in ("B", "E")
            assert e["ts"] >= last_ts, "timestamps must be monotonic"
            last_ts = e["ts"]
            if e["ph"] == "B":
                stack.append(e["name"])
            else:
                assert stack and stack[-1] == e["name"], "mismatched B/E"
                stack.pop()
        assert not stack, "unclosed B events"

    def test_schema_loadable_monotonic_matched(self):
        tr = SpanTracer()
        with tr.span("run"):
            with tr.span("ingest"):
                pass
            with tr.span("classify"):
                with tr.span("predict"):
                    pass
        doc = json.loads(json.dumps(tr.to_chrome_trace()))
        self._check_trace(doc)
        names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "B"]
        assert names == ["run", "ingest", "classify", "predict"]

    def test_sibling_subtrees_ordered_by_start(self):
        tr = SpanTracer()
        with tr.span("root"):
            with tr.span("first"):
                pass
            with tr.span("second"):
                pass
        ev = tr.trace_events()
        assert [e["name"] for e in ev] == [
            "root", "first", "first", "second", "second", "root",
        ]
        assert [e["ph"] for e in ev] == ["B", "B", "E", "B", "E", "E"]


class TestHistogram:
    def test_bucket_edges_le_semantics(self):
        h = Histogram("h", (), buckets=(1.0, 5.0, 10.0))
        for v in (0.5, 1.0):   # both land in the first bucket (le=1.0)
            h.observe(v)
        h.observe(1.0000001)   # just past the edge -> second bucket
        h.observe(10.0)        # exactly the last finite edge
        h.observe(10.0000001)  # overflow -> +Inf bucket
        assert h.bucket_counts() == [2, 1, 1, 1]
        assert h.count == 5
        assert h.cumulative() == [
            (1.0, 2), (5.0, 3), (10.0, 4), (math.inf, 5),
        ]

    def test_sum_tracks_observations(self):
        h = Histogram("h", (), buckets=(1.0,))
        h.observe(0.25)
        h.observe(4.0)
        assert h.sum == pytest.approx(4.25)

    def test_none_buckets_use_default_ladder(self):
        from knn_tpu.obs.metrics import DEFAULT_BUCKETS

        assert Histogram("h", (), buckets=None).buckets == DEFAULT_BUCKETS

    def test_rejects_bad_buckets(self):
        with pytest.raises(ValueError):
            Histogram("h", (), buckets=(1.0, 1.0))
        with pytest.raises(ValueError):
            Histogram("h", (), buckets=(1.0, math.inf))


class TestRegistry:
    def test_get_or_create_same_instrument(self):
        reg = MetricsRegistry()
        a = reg.counter("c_total", backend="tpu")
        b = reg.counter("c_total", backend="tpu")
        assert a is b
        other = reg.counter("c_total", backend="oracle")
        assert other is not a

    def test_counter_rejects_decrease(self):
        reg = MetricsRegistry()
        with pytest.raises(ValueError, match="cannot decrease"):
            reg.counter("c_total").add(-1)

    def test_kind_collision_rejected(self):
        reg = MetricsRegistry()
        reg.counter("x")
        with pytest.raises(TypeError, match="already registered"):
            reg.gauge("x")

    def test_histogram_bucket_conflict_rejected(self):
        reg = MetricsRegistry()
        h = reg.histogram("h_ms", buckets=(1.0, 10.0))
        assert reg.histogram("h_ms", buckets=(10.0, 1.0)) is h  # same ladder
        assert reg.histogram("h_ms") is h  # None defers to the existing one
        with pytest.raises(ValueError, match="conflicting"):
            reg.histogram("h_ms", buckets=(1.0, 5.0))

    def test_json_dump(self):
        reg = MetricsRegistry()
        reg.counter("c_total", backend="tpu").add(3)
        reg.histogram("h_ms", buckets=(1.0,)).observe(0.5)
        doc = json.loads(json.dumps(reg.to_json()))
        assert doc["c_total"][0] == {
            "labels": {"backend": "tpu"}, "kind": "counter", "value": 3,
        }
        hrec = doc["h_ms"][0]
        assert hrec["count"] == 1
        assert hrec["buckets"][-1]["le"] == "+Inf"

    def test_prometheus_text_format(self):
        reg = MetricsRegistry()
        reg.counter("knn_queries_total", help="rows classified",
                    backend="tpu").add(42)
        reg.gauge("knn_qps").set(1234.5)
        h = reg.histogram("knn_wall_ms", buckets=(1.0, 10.0))
        h.observe(0.5)
        h.observe(99.0)
        text = reg.to_prometheus()
        lines = text.strip().splitlines()
        assert "# HELP knn_queries_total rows classified" in lines
        assert "# TYPE knn_queries_total counter" in lines
        assert 'knn_queries_total{backend="tpu"} 42' in lines
        assert "# TYPE knn_qps gauge" in lines
        assert "knn_qps 1234.5" in lines
        assert 'knn_wall_ms_bucket{le="1"} 1' in lines
        assert 'knn_wall_ms_bucket{le="10"} 1' in lines
        assert 'knn_wall_ms_bucket{le="+Inf"} 2' in lines
        assert "knn_wall_ms_sum 99.5" in lines
        assert "knn_wall_ms_count 2" in lines
        # TYPE precedes samples for each family.
        assert lines.index("# TYPE knn_queries_total counter") < lines.index(
            'knn_queries_total{backend="tpu"} 42'
        )


class TestDisabledIsNoop:
    def test_span_is_shared_null(self):
        assert not obs.enabled()
        s1 = obs.span("anything", big="attr")
        s2 = obs.span("else")
        assert s1 is s2  # the shared singleton: no allocation per call
        with s1:
            pass
        assert obs.tracer().spans() == []

    def test_metric_helpers_record_nothing(self):
        assert not obs.enabled()
        obs.counter_add("c_total", 5)
        obs.gauge_set("g", 1)
        obs.histogram_observe("h", 2)
        assert obs.registry().instruments() == []


class TestCliRoundTrip:
    @pytest.fixture(scope="class")
    def paths(self):
        from tests import fixtures

        d = fixtures.datasets_dir()
        return str(d / "small-train.arff"), str(d / "small-test.arff")

    def test_metrics_json_and_trace(self, paths, tmp_path):
        from knn_tpu.cli import run

        m_path = tmp_path / "m.json"
        t_path = tmp_path / "t.json"
        out = io.StringIO()
        rc = run([paths[0], paths[1], "3", "--metrics-out", str(m_path),
                  "--trace-out", str(t_path), "--json"], stdout=out)
        # run() scopes the flag-driven enablement to the call.
        assert not obs.enabled()
        obs.reset()
        assert rc == 0
        m = json.loads(m_path.read_text())
        cli_rec = json.loads(out.getvalue().strip().splitlines()[-1])
        # --metrics-out and --json agree on the per-phase totals.
        assert cli_rec["phases"] == m["phases"]
        # Per-phase totals sum to ~the headline wall time. The absolute
        # floor covers the fixed sub-ms of ladder/metric bookkeeping that
        # sits inside classify but outside the predict child span: on a
        # fully warm path the small-fixture wall drops to ~2 ms, where
        # that constant alone exceeds 5% relative (surfaced when the
        # serving-PR CLI tests warmed more of the path ahead of this
        # test; the uncovered gap itself is unchanged at ~0.2 ms).
        wall = m["wall_ms"]
        assert wall > 0
        assert sum(m["phases"].values()) == pytest.approx(
            wall, rel=0.05, abs=0.5)
        # Perfetto trace: loadable, monotonic ts, matched B/E, >= 4 distinct
        # nested phases.
        trace = json.loads(t_path.read_text())
        TestPerfettoExport()._check_trace(trace)
        names = {e["name"] for e in trace["traceEvents"]}
        assert len(names) >= 4
        assert {"ingest", "classify", "predict"} <= names
        max_depth = depth = 0
        for e in trace["traceEvents"]:
            depth += 1 if e["ph"] == "B" else -1
            max_depth = max(max_depth, depth)
        assert max_depth >= 3  # e.g. classify > predict > dispatch

    def test_prometheus_out(self, paths, tmp_path):
        from knn_tpu.cli import run

        m_path = tmp_path / "m.prom"
        rc = run([paths[0], paths[1], "1", "--metrics-out", str(m_path)],
                 stdout=io.StringIO())
        obs.disable()
        obs.reset()
        assert rc == 0
        text = m_path.read_text()
        assert "# TYPE knn_queries_total counter" in text
        assert 'knn_queries_total{backend="tpu"}' in text

    def test_unwritable_out_fails_fast(self, paths, capsys):
        from knn_tpu.cli import run

        rc = run([paths[0], paths[1], "1",
                  "--metrics-out", "/no/such/dir/m.json"])
        obs.disable()
        obs.reset()
        # Rejected before any parse/compute: usage exit code (2).
        assert rc == 2
        assert "error:" in capsys.readouterr().err

    def test_sweep_carries_phases(self, paths, tmp_path):
        from knn_tpu.cli import run

        m_path = tmp_path / "m.json"
        out = io.StringIO()
        rc = run([paths[0], paths[1], "1", "--sweep-k", "1,5", "--engine",
                  "xla", "--metrics-out", str(m_path), "--json"], stdout=out)
        obs.disable()
        obs.reset()
        assert rc == 0
        m = json.loads(m_path.read_text())
        assert "sweep_k" in m["phases"]
        assert sum(m["phases"].values()) == pytest.approx(
            m["wall_ms"], rel=0.05
        )


class TestCollectiveCounters:
    """The live counters must equal comm_audit's analytic model EXACTLY."""

    def _problem(self, rng, n=400, q=96, d=6, c=5):
        train_x = rng.random((n, d), np.float32)
        train_y = rng.integers(0, c, n).astype(np.int32)
        test_x = rng.random((q, d), np.float32)
        return train_x, train_y, test_x, c

    def _counter_value(self, path):
        total = 0
        for inst in obs.registry().instruments():
            if inst.name != "knn_collective_bytes_total":
                continue
            if dict(inst.labels).get("path") == path:
                total += inst.value
        return total

    def test_train_sharded_bytes_match_model(self, global_obs, rng):
        from knn_tpu.parallel.comm_audit import model_train_sharded_bytes
        from knn_tpu.parallel.train_sharded import (
            predict_train_sharded, xla_shard_layout,
        )

        train_x, train_y, test_x, c = self._problem(rng)
        k, n_q, n_t, query_tile, train_tile = 5, 2, 2, 16, 64
        predict_train_sharded(
            train_x, train_y, test_x, k, c, mesh_shape=(n_q, n_t),
            query_tile=query_tile, train_tile=train_tile, engine="xla",
        )
        q_pad = -(-test_x.shape[0] // (n_q * query_tile)) * n_q * query_tile
        expected = model_train_sharded_bytes(q_pad // n_q, k, n_t)
        assert self._counter_value("train-sharded") == expected

    def test_ring_bytes_match_model(self, global_obs, rng):
        from knn_tpu.parallel.comm_audit import model_ring_bytes
        from knn_tpu.parallel.ring import predict_ring

        train_x, train_y, test_x, c = self._problem(rng)
        n_dev = 4
        predict_ring(
            train_x, train_y, test_x, 3, c, num_devices=n_dev, engine="full",
        )
        n_pad = -(-train_x.shape[0] // n_dev) * n_dev
        shard_rows = n_pad // n_dev
        expected = model_ring_bytes(
            shard_rows * train_x.shape[1] * 4, shard_rows * 4, n_dev
        )
        assert self._counter_value("ring") == expected

    def test_query_sharded_bytes_match_model(self, global_obs, rng):
        from knn_tpu.parallel.comm_audit import model_query_sharded_bytes
        from knn_tpu.parallel.query_sharded import predict_query_sharded

        train_x, train_y, test_x, c = self._problem(rng)
        n_dev, query_tile = 2, 16
        predict_query_sharded(
            train_x, train_y, test_x, 3, c, num_devices=n_dev,
            query_tile=query_tile, engine="xla",
        )
        q_pad = -(-test_x.shape[0] // (n_dev * query_tile)) * n_dev * query_tile
        expected = model_query_sharded_bytes(q_pad, train_x.shape[1])
        assert self._counter_value("query-sharded") == expected

    def test_static_audit_agrees_with_runtime_model(self, rng):
        """The lowering-derived byte count and the model fn the runtime
        counter uses are the same number — the audit asserts internally."""
        import jax.numpy as jnp

        from knn_tpu.parallel.comm_audit import audit_train_sharded
        from knn_tpu.parallel.mesh import make_mesh_2d
        from knn_tpu.parallel.train_sharded import build_train_sharded_fn

        train_x, train_y, test_x, c = self._problem(rng, n=256, q=64)
        k, query_tile, train_tile = 3, 32, 128
        mesh = make_mesh_2d(2, 2)
        fn = build_train_sharded_fn(
            mesh, k, c, "exact", query_tile, train_tile
        )
        lowered = fn.lower(
            jnp.zeros((256, 6), jnp.float32), jnp.zeros(256, jnp.int32),
            jnp.zeros((64, 6), jnp.float32), jnp.asarray(256, jnp.int32),
        ).as_text(dialect="stablehlo")
        measured, expected = audit_train_sharded(lowered, 32, k, 2)
        assert measured == expected


class TestTimingSatellites:
    def test_region_timer_early_read_raises(self):
        from knn_tpu.utils.timing import RegionTimer

        t = RegionTimer()
        with pytest.raises(RuntimeError, match="not finished"):
            t.ns
        with t:
            with pytest.raises(RuntimeError, match="not finished"):
                t.ms
        assert t.ms >= 0

    def test_region_timer_reuse_does_not_expose_stale_end(self):
        from knn_tpu.utils.timing import RegionTimer

        t = RegionTimer()
        with t:
            pass
        t.__enter__()  # reused: mid-region again
        with pytest.raises(RuntimeError, match="not finished"):
            t.ns
        t.__exit__()
        assert t.ns >= 0

    def test_maybe_profile_rejects_unwritable_dir(self, tmp_path):
        from knn_tpu.utils.timing import maybe_profile

        blocker = tmp_path / "a_file"
        blocker.write_text("")
        with pytest.raises(ValueError, match="not writable"):
            with maybe_profile(str(blocker / "trace")):
                pass

    def test_maybe_profile_creates_dir(self, tmp_path):
        from knn_tpu.utils.timing import maybe_profile

        target = tmp_path / "traces" / "run1"
        with maybe_profile(str(target)):
            pass
        assert target.is_dir()
