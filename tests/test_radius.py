"""Within-radius retrieval (fixed-shape masked formulation, a framework
extension; the reference has no retrieval API at all)."""

import numpy as np
import pytest

from knn_tpu.data.dataset import Dataset
from knn_tpu.models.knn import KNNClassifier, KNNRegressor, radius_neighbors_arrays


def _problem(rng, n=250, q=30, d=4):
    train_x = rng.uniform(0, 10, (n, d)).astype(np.float32)
    test_x = rng.uniform(0, 10, (q, d)).astype(np.float32)
    return train_x, test_x


class TestRadiusNeighbors:
    def test_matches_bruteforce_sets(self, rng):
        train_x, test_x = _problem(rng)
        radius = 6.0  # squared-distance radius
        d, i, mask = radius_neighbors_arrays(train_x, test_x, radius, 64)
        bf = ((test_x[:, None, :] - train_x[None, :, :]) ** 2).sum(-1)
        for row in range(test_x.shape[0]):
            want = set(np.nonzero(bf[row] <= radius)[0].tolist())
            got = set(i[row][mask[row]].tolist())
            assert got == want, f"row {row}"
        # Candidates come back sorted ascending by distance (inf-padded rows
        # compare equal, so restrict to pairs with a finite left element).
        left, right = d[:, :-1], d[:, 1:]
        finite = np.isfinite(left)
        assert (left[finite] <= right[finite]).all()

    def test_truncation_raises(self, rng):
        train_x, test_x = _problem(rng, n=100)
        with pytest.raises(ValueError, match="raise max_neighbors"):
            radius_neighbors_arrays(train_x, test_x, np.inf, max_neighbors=8)

    def test_max_neighbors_at_n_never_truncates(self, rng):
        train_x, test_x = _problem(rng, n=40, q=5)
        d, i, mask = radius_neighbors_arrays(train_x, test_x, np.inf, 40)
        assert mask.all()

    def test_model_methods(self, rng):
        train_x, test_x = _problem(rng, n=60, q=8)
        train = Dataset(
            train_x, np.zeros(60, np.int32),
            raw_targets=rng.normal(size=60).astype(np.float32),
        )
        test = Dataset(test_x, np.zeros(8, np.int32))
        for model in (KNNClassifier(k=1).fit(train), KNNRegressor(k=1).fit(train)):
            d, i, mask = model.radius_neighbors(test, 3.0, max_neighbors=60)
            assert d.shape == i.shape == mask.shape == (8, 60)

    def test_metric_respected(self, rng):
        train_x = np.array([[0.0, 0.0], [2.0, 2.0]], np.float32)
        test_x = np.array([[1.0, 1.0]], np.float32)
        # manhattan distances: 2 and 2; euclidean squared: 2 and 2. chebyshev: 1, 1.
        _, _, mask_c = radius_neighbors_arrays(
            train_x, test_x, 1.0, 2, metric="chebyshev"
        )
        assert mask_c.sum() == 2
        _, _, mask_m = radius_neighbors_arrays(
            train_x, test_x, 1.0, 2, metric="manhattan"
        )
        assert mask_m.sum() == 0
