"""Native C++ components: parser bit-parity with the Python implementation,
runtime kernel parity with the oracle, thread-count invariance.

Skipped wholesale when the shared libraries haven't been built (``make
native``).
"""

import numpy as np
import pytest

from knn_tpu.data import pyarff
from tests import fixtures

@pytest.fixture(scope="module")
def native_arff():
    return pytest.importorskip(
        "knn_tpu.native.arff_native",
        reason="native arff lib not built (run `make native`)",
    )


def _native_runtime():
    return pytest.importorskip(
        "knn_tpu.backends.native",
        reason="native runtime lib not built (run `make native`)",
    )


class TestNativeParser:
    @pytest.mark.parametrize("size", ["small", "medium", "large"])
    @pytest.mark.parametrize("split", ["train", "test"])
    def test_bit_parity_with_python_parser(self, native_arff, size, split):
        path = str(fixtures.datasets_dir() / f"{size}-{split}.arff")
        nat = native_arff.parse(path)
        py = pyarff.parse_arff_file(path)
        np.testing.assert_array_equal(nat.features, py.features)
        np.testing.assert_array_equal(nat.labels, py.labels)
        assert nat.relation == py.relation
        assert [a.name for a in nat.attributes] == [a.name for a in py.attributes]
        assert [a.type for a in nat.attributes] == [a.type for a in py.attributes]

    def test_dialect_nominal_quoted_missing(self, native_arff, tmp_path):
        p = tmp_path / "t.arff"
        p.write_text(
            "% comment\n@RELATION 'my rel'\n"
            "@attribute 'a b' NUMERIC\n"
            "@attribute c {red, 'dark blue'}\n"
            "@attribute class NUMERIC\n"
            "@data\n"
            "1.5,red,0\n"
            "?,'dark blue',1\n"
            "2,red\n"  # short row continued on next line
            "2\n"
        )
        nat = native_arff.parse(str(p))
        py = pyarff.parse_arff_file(str(p))
        np.testing.assert_array_equal(nat.labels, py.labels)
        assert nat.relation == "my rel"
        assert np.isnan(nat.features[1, 0]) and np.isnan(py.features[1, 0])
        assert nat.features[1, 1] == 1.0  # 'dark blue' -> index 1
        assert nat.attributes[1].nominal_values == ["red", "dark blue"]
        assert nat.num_instances == 3

    def test_quoted_splice_completes_numeric_token(self, native_arff, tmp_path):
        # A numeric-looking prefix continued by a quoted slice (1e'5' ->
        # 1e5) must stay one token and PARSE in an all-numeric file: the
        # fused eager line scan may not commit a conversion error before
        # the token's terminator is known (r4 review repro — the truncated
        # '1e' must never be converted on its own).
        p = tmp_path / "sp.arff"
        p.write_text(
            "@relation r\n@attribute a NUMERIC\n@attribute class NUMERIC\n"
            "@data\n1,1e'5'\n"
        )
        nat = native_arff.parse(str(p))
        py = pyarff.parse_arff_file(str(p))
        np.testing.assert_array_equal(nat.features, [[1.0]])
        np.testing.assert_array_equal(nat.labels, [100000])
        np.testing.assert_array_equal(nat.features, py.features)
        np.testing.assert_array_equal(nat.labels, py.labels)

    def test_crlf_numeric_file_parity(self, native_arff, tmp_path):
        # Plain CRLF endings ride the fused fast path (a '\r' directly
        # before '\n' is an EOL, not a bail); output must match the Python
        # parser and the LF rendering of the same file.
        p = tmp_path / "crlf.arff"
        body = ("@relation r\r\n@attribute a NUMERIC\r\n"
                "@attribute class NUMERIC\r\n@data\r\n"
                "1.5,0\r\n2.25,1\r\n7,2\r\n")
        p.write_bytes(body.encode())
        nat = native_arff.parse(str(p))
        py = pyarff.parse_arff_file(str(p))
        np.testing.assert_array_equal(
            nat.features.view(np.uint32), py.features.view(np.uint32))
        np.testing.assert_array_equal(nat.labels, [0, 1, 2])
        lf = tmp_path / "lf.arff"
        lf.write_bytes(body.replace("\r\n", "\n").encode())
        nat_lf = native_arff.parse(str(lf))
        np.testing.assert_array_equal(
            nat.features.view(np.uint32), nat_lf.features.view(np.uint32))

    def test_wide_row_exceeding_sample_window(self, native_arff, tmp_path):
        # Rows wider than the 64 KB row-estimate sample window (no newline
        # in the sample): the reservation heuristic must scale by bytes,
        # not by a row-count guess times d — the latter asked for a
        # multi-GB reserve on a 2 MB file (r4 review repro).
        d = 30000
        p = tmp_path / "wide.arff"
        with open(p, "w") as f:
            f.write("@relation w\n")
            for i in range(d):
                f.write(f"@attribute a{i} NUMERIC\n")
            f.write("@attribute class NUMERIC\n@data\n")
            for r in range(3):
                f.write(",".join(["1.5"] * d) + f",{r}\n")
        ds = native_arff.parse(str(p))
        assert ds.features.shape == (3, d)
        np.testing.assert_array_equal(ds.labels, [0, 1, 2])
        assert (ds.features == 1.5).all()

    def test_multiline_quoted_values_both_parsers(self, native_arff, tmp_path):
        # arff_lexer.cpp:159-188: a quoted value spans physical lines, the
        # newline is part of the value; an open '{' nominal list continues on
        # the next line (newlines are inter-token whitespace to the lexer).
        p = tmp_path / "ml.arff"
        p.write_text(
            "@relation 'two\nline rel'\n"
            "@attribute c {'re\nd', green,\n  blue}\n"
            "@attribute s string\n"
            "@attribute class NUMERIC\n"
            "@data\n"
            "'re\nd', 'one\ntwo', 0\n"
            "green, plain, 1\n"
            "blue, 'one\ntwo', 2\n"
        )
        nat = native_arff.parse(str(p))
        py = pyarff.parse_arff_file(str(p))
        assert nat.relation == py.relation == "two\nline rel"
        assert (
            nat.attributes[0].nominal_values
            == py.attributes[0].nominal_values
            == ["re\nd", "green", "blue"]
        )
        np.testing.assert_array_equal(
            nat.features, np.array([[0, 0], [1, 1], [2, 0]], np.float32)
        )
        np.testing.assert_array_equal(nat.features, py.features)
        np.testing.assert_array_equal(nat.labels, py.labels)
        assert (
            nat.attributes[1].string_values
            == py.attributes[1].string_values
            == ["one\ntwo", "plain"]
        )

    def test_multiline_quote_crlf_parity(self, native_arff, tmp_path):
        # CRLF file with a quoted value spanning lines: the reference scanner
        # reads raw bytes, so the '\r' before the line break is part of the
        # value — both parsers must preserve it identically (r3 review: the
        # Python join once stripped it while the native zero-copy slice kept
        # it).
        p = tmp_path / "crlfq.arff"
        p.write_bytes(
            b"@relation t\r\n@attribute s string\r\n"
            b"@attribute class NUMERIC\r\n@data\r\n"
            b"'a\r\nb',0\r\nplain,1\r\n"
        )
        nat = native_arff.parse(str(p))
        py = pyarff.parse_arff_file(str(p))
        assert (
            nat.attributes[0].string_values
            == py.attributes[0].string_values
            == ["a\r\nb", "plain"]
        )
        np.testing.assert_array_equal(nat.features, py.features)

    def test_multiline_row_error_cites_token_line(self, native_arff, tmp_path):
        # A bad numeric token AFTER a multi-line quoted cell must cite its
        # own physical line in both parsers (native: per-token t_line;
        # pyarff: per-token attribution through the quote-joined line).
        p = tmp_path / "loc.arff"
        p.write_text(
            "@relation t\n@attribute s string\n@attribute x NUMERIC\n"
            "@attribute class NUMERIC\n@data\n"
            "'a\nb', zz, 0\n"
        )
        with pytest.raises(ValueError, match=r"loc\.arff:7"):
            native_arff.parse(str(p))
        with pytest.raises(ValueError, match=r"loc\.arff:7"):
            pyarff.parse_arff_file(str(p))

    def test_embedded_nul_rejected_both_parsers(self, native_arff, tmp_path):
        # ADVICE r2: the parsers disagreed on a numeric cell with an embedded
        # NUL (native rejected via full-view consumption, pyarff accepted via
        # strtof's stop-at-NUL). Both now enforce the explicit token length.
        p = tmp_path / "nul.arff"
        p.write_bytes(
            b"@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
            b"@data\n1\x00x,0\n"
        )
        with pytest.raises(ValueError, match="cannot parse"):
            native_arff.parse(str(p))
        with pytest.raises(ValueError, match="cannot parse"):
            pyarff.parse_arff_file(str(p))

    def test_unterminated_quote_at_eof_both_parsers(self, native_arff, tmp_path):
        p = tmp_path / "uq.arff"
        p.write_text(
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
            "@data\n1,0\n'never closed\n2,1\n"
        )
        with pytest.raises(ValueError, match="unterminated"):
            native_arff.parse(str(p))
        with pytest.raises(ValueError, match="unterminated"):
            pyarff.parse_arff_file(str(p))

    def test_error_has_location(self, native_arff, tmp_path):
        p = tmp_path / "bad.arff"
        p.write_text("@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n@data\nzz,0\n")
        with pytest.raises(ValueError, match=r"bad\.arff:5"):
            native_arff.parse(str(p))

    def test_missing_file(self, native_arff):
        with pytest.raises(ValueError, match="cannot open"):
            native_arff.parse("/nonexistent/x.arff")

    def test_sparse_rejected(self, native_arff, tmp_path):
        p = tmp_path / "s.arff"
        p.write_text("@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n@data\n{0 1}\n")
        with pytest.raises(ValueError, match="sparse"):
            native_arff.parse(str(p))


def _random_arff(rng) -> str:
    """One random ARFF file exercising the dialect corners: mixed-case
    keywords, quoted names/values, nominal sets, `%` comments, blank lines,
    `?` missing cells, multi-line rows, scientific/negative numbers."""
    lines = []
    if rng.random() < 0.5:
        lines.append("% a leading comment")
    rel = rng.choice(["rel", "'quoted rel'", '"dq rel"'])
    lines.append(f"{rng.choice(['@relation', '@RELATION', '@Relation'])} {rel}")
    n_feat = int(rng.integers(1, 6))
    attrs = []
    for i in range(n_feat):
        kind = rng.choice(["numeric", "nominal"])
        name = rng.choice([f"a{i}", f"'attr {i}'"])
        if kind == "numeric":
            ty = rng.choice(["NUMERIC", "numeric", "REAL", "Integer"])
            lines.append(f"@attribute {name} {ty}")
            attrs.append(("numeric", None))
        else:
            vals = [f"v{j}" for j in range(int(rng.integers(2, 5)))]
            quoted = [f"'{v} x'" if rng.random() < 0.3 else v for v in vals]
            lines.append(f"@attribute {name} {{{', '.join(quoted)}}}")
            attrs.append(("nominal", [v.strip("'").strip() for v in quoted]))
    lines.append("@attribute class NUMERIC")
    if rng.random() < 0.3:
        lines.append("")
        lines.append("% mid-file comment")
    lines.append(rng.choice(["@data", "@DATA"]))
    n_rows = int(rng.integers(0, 12))
    for _ in range(n_rows):
        cells = []
        for kind, vals in attrs:
            if rng.random() < 0.1:
                cells.append("?")
            elif kind == "numeric":
                v = rng.choice([
                    str(int(rng.integers(-50, 50))),
                    f"{rng.normal():.6g}",
                    f"{rng.normal() * 1e-4:.3e}",
                ])
                cells.append(v)
            else:
                v = vals[int(rng.integers(0, len(vals)))]
                cells.append(f"'{v}'" if " " in v else v)
        cells.append(str(int(rng.integers(0, 4))))
        style = rng.random()
        if len(cells) > 2 and style < 0.2:  # split row across lines
            cut = int(rng.integers(1, len(cells)))
            # Trailing comma continues the row (reference-valid; a LEADING
            # comma on the continuation line truncates the reference and is
            # a located error here — covered in the malformed cases).
            lines.append(",".join(cells[:cut]) + ",")
            lines.append(",".join(cells[cut:]))
        elif style < 0.3:
            # Whitespace separates tokens exactly like commas (token-stream
            # dialect) — but quoted cells must keep their own quoting.
            lines.append(" ".join(cells))
        elif style < 0.4 and len(cells) > 1:
            cut = int(rng.integers(1, len(cells)))
            lines.append(",".join(cells[:cut]))  # row continues with NO comma
            lines.append(",".join(cells[cut:]))
        else:
            lines.append(",".join(cells))
    return "\n".join(lines) + "\n"


class TestFuzzDifferential:
    """The native parser and the Python parser are independent
    implementations of the same grammar (SURVEY.md §3.4); random valid files
    must produce bit-identical arrays, and malformed files must fail in BOTH
    with a location-bearing error."""

    def test_random_valid_files_bit_identical(self, native_arff, tmp_path):
        rng = np.random.default_rng(1234)
        for trial in range(40):
            p = tmp_path / f"fuzz{trial}.arff"
            p.write_text(_random_arff(rng))
            nat = native_arff.parse(str(p))
            py = pyarff.parse_arff_file(str(p))
            np.testing.assert_array_equal(
                nat.features, py.features, err_msg=p.read_text()
            )
            np.testing.assert_array_equal(nat.labels, py.labels)
            np.testing.assert_array_equal(nat.raw_targets, py.raw_targets)
            assert nat.relation == py.relation, p.read_text()
            assert [(a.name, a.type, a.nominal_values) for a in nat.attributes] == \
                [(a.name, a.type, a.nominal_values) for a in py.attributes]

    @pytest.mark.parametrize(
        "body",
        [
            "@relation r\n@attribute x NUMERIC\n@data\n",  # single attr: no feature cols is fine, but...
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n@data\nnotanum,0\n",
            "@relation r\n@attribute c {a,b}\n@attribute class NUMERIC\n@data\nz,0\n",
            "@relation r\n@bogus x\n@data\n",
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n@data\n{0 1}\n",
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n@data\n1,?\n",
            "@relation r\n@attribute c {a,,b}\n@attribute class NUMERIC\n@data\na,0\n",
            "@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n@data\n1,,0\n",
            "@relation r\n@attribute a NUMERIC\n@attribute b NUMERIC\n"
            "@attribute class NUMERIC\n@data\n1,2\n,0\n",
            "@relation r\n@attribute c {a,b,}\n@attribute class NUMERIC\n@data\nb,0\n",
            "@relation r\n@attribute c {a,''}\n@attribute class NUMERIC\n@data\na,0\n",
            "@relation r\n@attribute c {}\n@attribute class NUMERIC\n@data\n",
            "@relation r\n@attribute a NUMERIC\n@attribute b NUMERIC\n"
            "@attribute class NUMERIC\n@data\n1,2,\x0c\n3\n",
            "@relation \"'q'\"\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
            "@data\n1,0\n",
            "@relation\x0cfoo\n@attribute x NUMERIC\n@attribute class NUMERIC\n"
            "@data\n1,0\n",
        ],
        ids=["no-rows-1attr", "bad-number", "bad-nominal",
             "bad-keyword", "sparse", "missing-label", "empty-nominal-decl",
             "empty-data-field", "leading-comma-continuation",
             "trailing-comma-nominal-valid", "quoted-empty-nominal",
             "empty-nominal-set-valid", "formfeed-after-comma",
             "nested-quoted-relation", "formfeed-keyword"],
    )
    def test_malformed_fails_in_both_or_neither(self, native_arff, tmp_path, body):
        p = tmp_path / "m.arff"
        p.write_text(body)
        nat_err = py_err = None
        nat = py = None
        try:
            nat = native_arff.parse(str(p))
        except ValueError as e:
            nat_err = str(e)
        try:
            py = pyarff.parse_arff_file(str(p))
        except ValueError as e:
            py_err = str(e)
        assert (nat_err is None) == (py_err is None), (
            f"parsers disagree on validity: native={nat_err!r} python={py_err!r}"
        )
        if nat_err is not None:
            import re

            assert re.search(r":\d+: ", nat_err), f"no location in {nat_err!r}"
            assert re.search(r":\d+: ", py_err), f"no location in {py_err!r}"
        if nat is not None and py is not None:
            np.testing.assert_array_equal(nat.features, py.features)
            np.testing.assert_array_equal(nat.labels, py.labels)
            assert nat.relation == py.relation
            assert [(a.name, a.type, a.nominal_values) for a in nat.attributes] == \
                [(a.name, a.type, a.nominal_values) for a in py.attributes]

    @pytest.mark.parametrize(
        "tok",
        ["0x10", "0x1.8p1", "-0x.8", "1_0", "١", "１", "inf", "-infinity",
         "nan", "-nan", "nan(x7_)", "1e999", "1e-999", ".5", "5.", "+.25",
         "0x", "0x1p", "1.5e", ".", "+", "1.2.3", "Infinit", "0X1F",
         "7.038531e-26"],  # strtof single-rounds; float64→float32 would be 1 ulp off
        ids=repr,
    )
    def test_numeric_token_parity(self, native_arff, tmp_path, tok):
        """Numeric cells go through C strtof in the native parser
        (arff_c.cc::cell_to_float); the Python parser must accept and reject
        the exact same token set with BIT-identical float32 values — Python
        float()'s extras (digit underscores, non-ASCII digits) must fail,
        strtof's extras (hex floats, nan(...)) must succeed, and rounding and
        NaN sign must match at the bit level (last-ulp near-halfway decimals,
        '-nan' sign bit)."""
        p = tmp_path / "tok.arff"
        p.write_text(
            "@relation t\n@attribute a NUMERIC\n@attribute class NUMERIC\n"
            f"@data\n{tok},1\n"
        )
        nat_val = py_val = nat_err = py_err = None
        try:
            nat_val = native_arff.parse(str(p)).features[0, 0]
        except ValueError as e:
            nat_err = str(e)
        try:
            py_val = pyarff.parse_arff_file(str(p)).features[0, 0]
        except ValueError as e:
            py_err = str(e)
        assert (nat_err is None) == (py_err is None), (
            f"validity disagrees for {tok!r}: native={nat_err!r} python={py_err!r}"
        )
        if nat_err is None:
            assert np.float32(py_val).tobytes() == np.float32(nat_val).tobytes(), (
                f"bit mismatch for {tok!r}: python={py_val!r} native={nat_val!r}"
            )

    def test_quoted_content_preserved_verbatim(self, native_arff, tmp_path):
        """The reference lexer copies chars between quotes as-is
        (arff_lexer.cpp:159-188): `' '` is the one-space token — distinct
        from an empty field — and inner spaces survive."""
        p = tmp_path / "q.arff"
        p.write_text(
            "@relation r\n"
            "@attribute c {' ', 'a  b', plain}\n"
            "@attribute class NUMERIC\n"
            "@data\n"
            "' ',0\n"
            "'a  b',1\n"
            "plain,2\n"
        )
        nat = native_arff.parse(str(p))
        py = pyarff.parse_arff_file(str(p))
        assert nat.attributes[0].nominal_values == [" ", "a  b", "plain"]
        assert py.attributes[0].nominal_values == [" ", "a  b", "plain"]
        np.testing.assert_array_equal(nat.features, [[0.0], [1.0], [2.0]])
        np.testing.assert_array_equal(py.features, nat.features)


class TestOnDemandBuild:
    def test_compile_failure_is_loud(self, tmp_path, monkeypatch):
        """A broken .cc must raise NativeBuildError (with compiler stderr),
        not OSError — the registry swallows OSError as 'not built', which
        would silently drop the native backends."""
        from knn_tpu import native as native_pkg

        bad = tmp_path / "bad.cc"
        bad.write_text("this is not C++\n")
        monkeypatch.setitem(native_pkg._SOURCES, "libbad.so", (bad, []))
        monkeypatch.setattr(native_pkg, "_LIB_DIR", tmp_path / "lib")
        with pytest.raises(native_pkg.NativeBuildError, match="libbad"):
            native_pkg.build_if_missing("libbad.so")

    def test_missing_source_and_lib_returns_path(self, tmp_path, monkeypatch):
        """No source and no prebuilt lib → return the (absent) path so CDLL
        raises plain OSError and the registry degrades gracefully."""
        from knn_tpu import native as native_pkg

        monkeypatch.setitem(
            native_pkg._SOURCES, "libgone.so", (tmp_path / "gone.cc", [])
        )
        monkeypatch.setattr(native_pkg, "_LIB_DIR", tmp_path / "lib")
        out = native_pkg.build_if_missing("libgone.so")
        assert not out.exists()


class TestNativeRuntime:
    def test_matches_oracle(self, rng):
        nb = _native_runtime()
        from knn_tpu.backends.oracle import knn_oracle

        n, q, d, k, c = 500, 64, 5, 7, 6
        train_x = rng.integers(0, 4, (n, d)).astype(np.float32)
        train_y = rng.integers(0, c, n).astype(np.int32)
        test_x = np.concatenate(
            [train_x[:20], rng.integers(0, 4, (q - 20, d)).astype(np.float32)]
        )
        want = knn_oracle(train_x, train_y, test_x, k, c)
        got = nb.knn_native(train_x, train_y, test_x, k, c, num_threads=1)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("threads", [2, 3, 8])
    def test_thread_count_invariance(self, rng, threads):
        nb = _native_runtime()
        n, q, d, k, c = 300, 50, 4, 5, 5
        train_x = rng.normal(size=(n, d)).astype(np.float32)
        train_y = rng.integers(0, c, n).astype(np.int32)
        test_x = rng.normal(size=(q, d)).astype(np.float32)
        serial = nb.knn_native(train_x, train_y, test_x, k, c, num_threads=1)
        mt = nb.knn_native(train_x, train_y, test_x, k, c, num_threads=threads)
        np.testing.assert_array_equal(serial, mt)

    @pytest.mark.skipif(
        not fixtures.using_reference_datasets(), reason="reference datasets required"
    )
    @pytest.mark.parametrize("size,k", [("small", 1), ("small", 5), ("medium", 5)])
    def test_golden_accuracy(self, size, k, request):
        nb = _native_runtime()
        from knn_tpu.utils.evaluate import confusion_matrix, accuracy

        train, test = request.getfixturevalue(size)
        preds = nb.knn_native(
            train.features, train.labels, test.features, k, train.num_classes,
            num_threads=2,
        )
        acc = accuracy(confusion_matrix(preds, test.labels, test.num_classes))
        assert round(acc, 4) == fixtures.GOLDEN_ACCURACY[(size, k)]

    def test_invalid_args_rejected(self, rng):
        nb = _native_runtime()
        train_x = rng.normal(size=(10, 3)).astype(np.float32)
        train_y = np.zeros(10, np.int32)
        test_x = rng.normal(size=(4, 3)).astype(np.float32)
        with pytest.raises(ValueError, match="rc=2"):
            nb.knn_native(train_x, train_y, test_x, 11, 1)  # k > n
