"""Native C++ components: parser bit-parity with the Python implementation,
runtime kernel parity with the oracle, thread-count invariance.

Skipped wholesale when the shared libraries haven't been built (``make
native``).
"""

import numpy as np
import pytest

from knn_tpu.data import pyarff
from tests import fixtures

@pytest.fixture(scope="module")
def native_arff():
    return pytest.importorskip(
        "knn_tpu.native.arff_native",
        reason="native arff lib not built (run `make native`)",
    )


def _native_runtime():
    return pytest.importorskip(
        "knn_tpu.backends.native",
        reason="native runtime lib not built (run `make native`)",
    )


class TestNativeParser:
    @pytest.mark.parametrize("size", ["small", "medium", "large"])
    @pytest.mark.parametrize("split", ["train", "test"])
    def test_bit_parity_with_python_parser(self, native_arff, size, split):
        path = str(fixtures.datasets_dir() / f"{size}-{split}.arff")
        nat = native_arff.parse(path)
        py = pyarff.parse_arff_file(path)
        np.testing.assert_array_equal(nat.features, py.features)
        np.testing.assert_array_equal(nat.labels, py.labels)
        assert nat.relation == py.relation
        assert [a.name for a in nat.attributes] == [a.name for a in py.attributes]
        assert [a.type for a in nat.attributes] == [a.type for a in py.attributes]

    def test_dialect_nominal_quoted_missing(self, native_arff, tmp_path):
        p = tmp_path / "t.arff"
        p.write_text(
            "% comment\n@RELATION 'my rel'\n"
            "@attribute 'a b' NUMERIC\n"
            "@attribute c {red, 'dark blue'}\n"
            "@attribute class NUMERIC\n"
            "@data\n"
            "1.5,red,0\n"
            "?,'dark blue',1\n"
            "2,red\n"  # short row continued on next line
            "2\n"
        )
        nat = native_arff.parse(str(p))
        py = pyarff.parse_arff_file(str(p))
        np.testing.assert_array_equal(nat.labels, py.labels)
        assert nat.relation == "my rel"
        assert np.isnan(nat.features[1, 0]) and np.isnan(py.features[1, 0])
        assert nat.features[1, 1] == 1.0  # 'dark blue' -> index 1
        assert nat.attributes[1].nominal_values == ["red", "dark blue"]
        assert nat.num_instances == 3

    def test_error_has_location(self, native_arff, tmp_path):
        p = tmp_path / "bad.arff"
        p.write_text("@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n@data\nzz,0\n")
        with pytest.raises(ValueError, match=r"bad\.arff:5"):
            native_arff.parse(str(p))

    def test_missing_file(self, native_arff):
        with pytest.raises(ValueError, match="cannot open"):
            native_arff.parse("/nonexistent/x.arff")

    def test_sparse_rejected(self, native_arff, tmp_path):
        p = tmp_path / "s.arff"
        p.write_text("@relation r\n@attribute x NUMERIC\n@attribute class NUMERIC\n@data\n{0 1}\n")
        with pytest.raises(ValueError, match="sparse"):
            native_arff.parse(str(p))


class TestNativeRuntime:
    def test_matches_oracle(self, rng):
        nb = _native_runtime()
        from knn_tpu.backends.oracle import knn_oracle

        n, q, d, k, c = 500, 64, 5, 7, 6
        train_x = rng.integers(0, 4, (n, d)).astype(np.float32)
        train_y = rng.integers(0, c, n).astype(np.int32)
        test_x = np.concatenate(
            [train_x[:20], rng.integers(0, 4, (q - 20, d)).astype(np.float32)]
        )
        want = knn_oracle(train_x, train_y, test_x, k, c)
        got = nb.knn_native(train_x, train_y, test_x, k, c, num_threads=1)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("threads", [2, 3, 8])
    def test_thread_count_invariance(self, rng, threads):
        nb = _native_runtime()
        n, q, d, k, c = 300, 50, 4, 5, 5
        train_x = rng.normal(size=(n, d)).astype(np.float32)
        train_y = rng.integers(0, c, n).astype(np.int32)
        test_x = rng.normal(size=(q, d)).astype(np.float32)
        serial = nb.knn_native(train_x, train_y, test_x, k, c, num_threads=1)
        mt = nb.knn_native(train_x, train_y, test_x, k, c, num_threads=threads)
        np.testing.assert_array_equal(serial, mt)

    @pytest.mark.skipif(
        not fixtures.using_reference_datasets(), reason="reference datasets required"
    )
    @pytest.mark.parametrize("size,k", [("small", 1), ("small", 5), ("medium", 5)])
    def test_golden_accuracy(self, size, k, request):
        nb = _native_runtime()
        from knn_tpu.utils.evaluate import confusion_matrix, accuracy

        train, test = request.getfixturevalue(size)
        preds = nb.knn_native(
            train.features, train.labels, test.features, k, train.num_classes,
            num_threads=2,
        )
        acc = accuracy(confusion_matrix(preds, test.labels, test.num_classes))
        assert round(acc, 4) == fixtures.GOLDEN_ACCURACY[(size, k)]

    def test_invalid_args_rejected(self, rng):
        nb = _native_runtime()
        train_x = rng.normal(size=(10, 3)).astype(np.float32)
        train_y = np.zeros(10, np.int32)
        test_x = rng.normal(size=(4, 3)).astype(np.float32)
        with pytest.raises(ValueError, match="rc=2"):
            nb.knn_native(train_x, train_y, test_x, 11, 1)  # k > n
