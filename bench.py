"""Benchmark: the BASELINE.json headline metric plus the secondary configs.

The default run classifies large-test.arff (1,718 queries) against
large-train.arff (30,803 rows, 11 features) at k=5 on the available
accelerator, then also runs the secondary configs (mnist / xl / xxl /
ingest / sharded / kneighbors / sweepk). Two JSON lines go to stdout:
first the FULL record (headline + every config with per-trial lists,
also written to build/bench_full.json), then a compact summary as the
FINAL line — headline value plus per-config medians, kept under
``SUMMARY_BUDGET`` bytes so the driver's ~2 KB tail capture always parses
it (VERDICT r4 #1; r4's single full-record line overflowed the capture
and the round artifact lost its headline):

  {"metric": "large_k5_query_throughput", "value": N, "unit": "queries/sec",
   "vs_baseline": N, "accuracy": A, "step_ms_median": M,
   "configs": {"mnist784": {...medians...}, "xl": {...}, ...}}

Diagnostics go to stderr. ``--config
mnist|xl|xxl|ingest|sharded|kneighbors|sweepk|serving|headline`` runs a
single config and prints just its record:

- mnist      — BASELINE.json config-5 shape (65,536 x 784 synthetic, 2,048
               queries, k=5) through the Pallas kernel (MXU distance form).
- xl         — ~1M train rows, k=10, lane-striped kernel.
- xxl        — ~10M train rows, k=5, ~640 MB train in HBM; stripe vs XLA
               tiled bit-exactness cross-check.
- ingest     — ARFF parse throughput (native C++ + pure-Python parsers).
- sharded    — the distributed (shard_map) query-sharded path routed through
               the stripe kernel on a 1-device mesh: proves the multi-chip
               code path runs at single-chip headline throughput per chip.
- kneighbors — model retrieval API wall latency per candidate engine.
- sweepk     — sweep_k({1,5,10}) vs three single-k runs vs one k=10 run at
               two train scales: the measured one-retrieval-many-k claim.
- serving    — the micro-batching engine (knn_tpu/serve/) under concurrent
               closed-loop load at several concurrency levels: p50/p99
               per-request latency + QPS, coalesced dispatch vs naive
               sequential per-call dispatch, with dropped/deadline-expired
               counters riding the record.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np

BASELINE_QPS = 138.6  # reference serial, large k=5 (BASELINE.md)
GOLDEN_ACC = 0.9948
K = 5


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def load_large():
    from knn_tpu.data.arff import load_arff

    ref = Path("/root/reference/datasets")
    if ref.exists():
        return (
            load_arff(str(ref / "large-train.arff")),
            load_arff(str(ref / "large-test.arff")),
            True,
        )
    # Synthetic fallback with the same shapes.
    import subprocess

    out = Path(__file__).parent / "build" / "fixtures"
    if not (out / "large-train.arff").exists():
        subprocess.run(
            [sys.executable, str(Path(__file__).parent / "scripts" / "make_fixtures.py"), str(out)],
            check=True,
        )
    return (
        load_arff(str(out / "large-train.arff")),
        load_arff(str(out / "large-test.arff")),
        False,
    )


# The timing/slope primitives live in knn_tpu.obs.bench_timing (one
# methodology for bench.py and every scripts/tune_* sweep); the private
# aliases keep this file's call sites and historical probe scripts stable.
from knn_tpu.obs.bench_timing import (  # noqa: E402
    PEAK_TF_BF16,
    PEAK_TF_F32,
    drop_superroofline as _drop_superroofline,
    interleaved_slope_trials as _interleaved_slope_trials,
    median as _median,
    pipelined_slope as _pipelined_slope,
    slope_trials as _slope_trials,
    spread as _spread,
    timed_batch as _timed_batch,
)


def bench_mnist():
    """BASELINE.json config 5: wide-feature KNN via the Pallas kernels.

    Both forms ride the lane-striped kernel at (1024, 2048) blocks with
    hoisted norms (r4): bf16 stores the train operand AS bf16 (half the
    per-query-tile train re-stream, 2x MXU rate); f32 "fast" measured ~1.6x
    the old merge-kernel route in the same session. f32/bf16 trials
    interleave (VERDICT r2 #1) so device-load variance can't erase the
    comparison."""
    import jax
    import jax.numpy as jnp

    from knn_tpu.ops.pallas_knn import (
        knn_pallas_stripe_candidates, stripe_prepare_queries,
        stripe_prepare_train,
    )

    n, q, d, k = 65536, 2048, 784, 5
    rng = np.random.default_rng(0)
    log(f"synthetic MNIST-shaped config: {n}x{d} train, {q} queries, k={k}")
    train_x = rng.random((n, d), np.float32)
    test_x = rng.random((q, d), np.float32)

    R_LO, R_HI = 10, 40
    sbq, sbn = 1024, 2048
    txT_h, d_pad = stripe_prepare_train(train_x, sbn)
    txf = jnp.asarray(txT_h)                 # f32-stored train operand
    txb = jnp.asarray(txT_h, jnp.bfloat16)   # bf16-stored train operand
    # One DISTINCT query buffer per dispatch: the measurement layers can
    # dedupe repeated (executable, inputs) executions, which silently
    # collapses a repeat-buffer slope to enqueue cost (observed on v5e:
    # a 3 ms kernel "measuring" 0.02 ms/step).
    sbufs = [
        jnp.asarray(stripe_prepare_queries(
            test_x + np.float32(i) * 1e-6, sbq, d_pad))
        for i in range(R_HI)
    ]
    jax.block_until_ready(sbufs)
    bufs = sbufs  # same layout serves both precisions

    def step_f32(qb):
        return knn_pallas_stripe_candidates(
            txf, qb, n, k, block_q=sbq, block_n=sbn, d_true=d,
            precision="fast", assume_finite=True,  # uniform [0,1) synthetic
        )

    def step_bf16(qb):
        return knn_pallas_stripe_candidates(
            txb, qb, n, k, block_q=sbq, block_n=sbn, d_true=d,
            precision="bf16", assume_finite=True,  # uniform [0,1) synthetic
        )

    # Attribution row (VERDICT r4 #3): the bare MXU distance step — the
    # same bf16 contraction via XLA with a min-reduce epilogue (kills the
    # [q, n] output traffic) and NO selection fold. The delta to the full
    # kernel step is the selection budget; with selection's VPU cost known
    # from topk_net.program_cost, the composed ceiling is documented in
    # docs/KERNELS.md (r5: ~118 TF/s on this shape — the kernel measures
    # 93-96% of it, so the r4 "=>135 TF" aspiration is past the roofline).
    tx_bf = jnp.asarray(train_x, jnp.bfloat16)

    @jax.jit
    def step_matmul(qb):
        cross = jax.lax.dot_general(
            qb[:, :d].astype(jnp.bfloat16), tx_bf,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return jnp.min(cross, axis=1)

    tx_f32 = jnp.asarray(train_x)

    @jax.jit
    def step_matmul_f32(qb):
        cross = jax.lax.dot_general(
            qb[:, :d], tx_f32,
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return jnp.min(cross, axis=1)

    # Compile both, then check bf16-vs-f32 neighbor recall on one buffer
    # (the parity guard VERDICT r2 #1 keeps: the bf16 form must stay a
    # faithful retrieval, not just a fast one).
    t0 = time.monotonic()
    _, idx_f32 = step_f32(bufs[0])
    idx_f32 = np.asarray(idx_f32)
    _, idx_b = step_bf16(sbufs[0])
    idx_b = np.asarray(idx_b)
    log(f"compile+first runs: {time.monotonic() - t0:.2f}s")
    recall = np.mean([
        len(set(idx_f32[i]) & set(idx_b[i])) / k for i in range(q)
    ])
    log(f"bf16 vs f32 stripe recall@{k}: {recall:.4f}")

    np.asarray(step_matmul(sbufs[0]))  # compile
    np.asarray(step_matmul_f32(sbufs[0]))
    slopes = _interleaved_slope_trials(
        {"f32": (step_f32, bufs), "bf16": (step_bf16, sbufs),
         "matmul": (step_matmul, sbufs),
         "matmul_f32": (step_matmul_f32, sbufs)}, R_LO, R_HI,
    )
    # The flop count per step bounds every case identically, but the PEAK
    # depends on the case's operand dtype: filtering an f32 trial against
    # the bf16 peak admits slopes that are physically impossible for f32
    # (ADVICE r5 #3) — so each case is filtered against its own roofline
    # before medians, or the record can carry impossible numbers.
    case_peak = {"f32": PEAK_TF_F32, "matmul_f32": PEAK_TF_F32,
                 "bf16": PEAK_TF_BF16, "matmul": PEAK_TF_BF16}
    for name in slopes:
        slopes[name] = _drop_superroofline(
            slopes[name], 2 * q * n * d, peak_tf=case_peak[name]
        )
    per_step, bf16_step = _median(slopes["f32"]), _median(slopes["bf16"])
    mm_step = _median(slopes["matmul"])
    mm32_step = _median(slopes["matmul_f32"])
    log(f"bare bf16 matmul (attribution): {mm_step*1e3:.2f} ms "
        f"({2*q*n*d/mm_step/1e12:.0f} Tflop/s); selection budget "
        f"{(bf16_step-mm_step)*1e3:.2f} ms; bare f32 matmul "
        f"{mm32_step*1e3:.2f} ms ({2*q*n*d/mm32_step/1e12:.0f} Tflop/s)")
    qps = q / per_step
    tflops = 2 * q * n * d / per_step / 1e12
    log(f"f32 stripe kernel: {per_step*1e3:.2f} ms/step ({qps:.0f} q/s)")
    log(f"bf16 stripe kernel: {bf16_step*1e3:.2f} ms/step "
        f"({q/bf16_step:.0f} q/s, {2*q*n*d/bf16_step/1e12:.0f} Tflop/s)")
    return {
        "metric": "mnist784_k5_query_throughput",
        "value": round(qps, 1),
        "unit": "queries/sec",
        "vs_baseline": None,
        "tflops": round(tflops, 1),
        **_spread(slopes["f32"]),
        "bf16_qps": round(q / bf16_step, 1),
        "bf16_tflops": round(2 * q * n * d / bf16_step / 1e12, 1),
        **{f"bf16_{k2}": v for k2, v in _spread(slopes["bf16"]).items()},
        "bf16_engine": "stripe(1024,2048), train stored bf16",
        "bf16_recall_at_k": round(float(recall), 4),
        "bf16_matmul_ms": round(mm_step * 1e3, 3),
        "bf16_matmul_tflops": round(2 * q * n * d / mm_step / 1e12, 1),
        "bf16_matmul_ms_trials": [
            round(s * 1e3, 3) for s in slopes["matmul"]
        ],
        "f32_matmul_ms": round(mm32_step * 1e3, 3),
        "f32_matmul_tflops": round(2 * q * n * d / mm32_step / 1e12, 1),
    }


def _tiled_large(train, reps):
    """THE xl/xxl scale dataset: large-train tiled ``reps``x, de-duplicated
    with float32 noise (a float64 normal at 10M x 11 is an ~880 MB
    temporary). One definition so every config benchmarks the same data."""
    rng = np.random.default_rng(0)
    feats = np.tile(train.features, (reps, 1))
    feats += 1e-3 * rng.standard_normal(feats.shape, dtype=np.float32)
    return feats, np.tile(train.labels, reps)


def _scaled_stripe_run(reps_tile, k, block_q, block_n, r_lo, r_hi):
    """Shared core for the xl/xxl scale configs: tile large-train
    ``reps_tile``x with float32 dedup noise, run the lane-striped classify at
    the given blocks with one DISTINCT query buffer per dispatch, and return
    ``(train, test, feats, labels, per_step_seconds, first_preds)``."""
    import jax
    import jax.numpy as jnp

    from knn_tpu.ops.pallas_knn import (
        knn_stripe_classify, stripe_inputs_finite, stripe_prepare_train,
        stripe_prepare_queries,
    )

    train, test, _ = load_large()
    feats, labels = _tiled_large(train, reps_tile)
    n, d_true = feats.shape
    log(f"scaled config: {n:,} train rows x {d_true} features, "
        f"{test.num_instances} queries, k={k}")
    finite = stripe_inputs_finite(feats, test.features)
    txT_h, d_pad = stripe_prepare_train(feats, block_n)
    txj = jnp.asarray(txT_h)
    del txT_h
    tyj = jnp.asarray(labels)
    nvalid = jnp.asarray(n, jnp.int32)
    bufs = [
        jnp.asarray(stripe_prepare_queries(
            test.features + np.float32(i) * 1e-7, block_q, d_pad))
        for i in range(r_hi)  # one distinct buffer per dispatch (dedupe-proof)
    ]
    jax.block_until_ready(bufs)

    def step(qb):
        return knn_stripe_classify(
            txj, tyj, qb, nvalid, k=k, num_classes=train.num_classes,
            block_q=block_q, block_n=block_n, d_true=d_true,
            assume_finite=finite,
        )

    t0 = time.monotonic()
    preds = np.asarray(step(bufs[0]))[: test.num_instances]
    log(f"compile+first run: {time.monotonic() - t0:.2f}s")
    trials = _slope_trials(step, bufs, r_lo, r_hi)
    log(f"{_median(trials)*1e3:.2f} ms/step median of {len(trials)} "
        f"(trials: {[round(t*1e3, 2) for t in trials]})")
    return train, test, feats, labels, trials, preds


def bench_xl():
    """BASELINE.json config 4 scale: large-train tiled ~33x (~1M rows), k=10,
    lane-striped Pallas kernel on one chip. Swept on v5e: k=10 candidate
    scratch is 2x the k=5 headline's, so the query block shrinks; huge train
    blocks amortize the selection rounds. (The train-sharded multi-chip
    variant of this config is validated on the CPU mesh — tests/test_parallel
    and __graft_entry__.dryrun_multichip — since one real chip is available.)"""
    import functools

    import jax
    import jax.numpy as jnp

    k = 10
    train, test, feats, _, trials, _ = _scaled_stripe_run(
        reps_tile=33, k=k, block_q=64, block_n=12288, r_lo=5, r_hi=20,
    )
    per_step = _median(trials)
    q = test.num_instances
    n = feats.shape[0]
    qps = q / per_step
    dist_rate = q * n / per_step

    # Hardware approximate selection at the scale where it could plausibly
    # win (VERDICT r3 #4): lax.approx_max_k over the full distance matrix,
    # recall measured against the exact stripe candidates. This is the
    # measurement that decides whether --approx earns its API surface.
    #
    # Run on a RANDOM 1M x 11 set of the same shape, not the tiled arrays:
    # on the 33x tiling every query has ~33 near-identical candidates, so
    # two selectors that see even slightly different distance values pick
    # near-disjoint tie subsets — approx(matmul) scored against the exact
    # STRIPE (subtraction-form) candidates measured 0.002 recall there
    # (r4), which r5 re-measurement attributes to that cross-form tie
    # divergence (same-values approx recall on the tiled set is ~0.99;
    # predict_arrays' r5 sampled-recall guard measures the same-values
    # form). Random data sidesteps the tie pathology so this row measures
    # approx selection itself.
    from knn_tpu.ops.pallas_knn import stripe_candidates_arrays

    rng = np.random.default_rng(7)
    rnd_train = rng.random((feats.shape[0], feats.shape[1]), np.float32)
    rnd_test = rng.random((q, feats.shape[1]), np.float32)
    _, exact_idx = stripe_candidates_arrays(rnd_train, rnd_test, k)

    @functools.partial(jax.jit, static_argnames=("k", "recall_target"))
    def approx_step(tx, qx, k, recall_target):
        d2 = (
            jnp.sum(qx * qx, axis=1, keepdims=True)
            - 2.0 * qx @ tx.T
            + jnp.sum(tx * tx, axis=1)[None, :]
        )
        _, idx = jax.lax.approx_max_k(-d2, k, recall_target=recall_target)
        return idx.astype(jnp.int32)

    txj = jnp.asarray(rnd_train)
    qbufs = [jnp.asarray(rnd_test + np.float32(i) * 1e-7) for i in range(8)]
    jax.block_until_ready(qbufs)
    approx_idx = np.asarray(approx_step(txj, qbufs[0], k, 0.95))
    idx_recall = float(np.mean([
        len(set(exact_idx[i]) & set(approx_idx[i])) / k for i in range(q)
    ]))
    approx_trials = _slope_trials(
        lambda qb: approx_step(txj, qb, k, 0.95), qbufs, 2, 8, trials=3,
    )
    approx_qps = q / _median(approx_trials)
    log(f"approx_max_k (full-matrix, random 1M, recall_target=0.95): "
        f"{_median(approx_trials)*1e3:.1f} ms/step ({approx_qps:,.0f} q/s), "
        f"recall@{k} vs exact stripe = {idx_recall:.4f}")
    return {
        "metric": "xl_1M_k10_query_throughput",
        "value": round(qps, 1),
        "unit": "queries/sec",
        "vs_baseline": None,
        "train_rows": int(n),
        "dist_evals_per_sec": round(dist_rate / 1e9, 1),
        "dist_unit": "Gdist/s",
        **_spread(trials),
        "approx_qps": round(approx_qps, 1),
        "approx_recall_at_k": round(idx_recall, 4),
        "approx_dataset": "random 1M x 11 (tiled data is adversarial to "
                          "approx_max_k's positional binning: recall 0.002)",
        "approx_step_ms_trials": [round(t * 1e3, 2) for t in approx_trials],
        "approx_wins": bool(approx_qps > qps),
    }


def bench_xxl():
    """Single-chip scale proof: ~10M train rows (large-train tiled ~325x,
    de-duplicated with noise), k=5 at the headline blocks (the grid just
    streams ~4.9k train tiles). The transposed train matrix is ~640 MB in
    HBM — far past anything the reference could touch — and the result is
    cross-checked for bit-exactness against the XLA tiled formulation on the
    same arrays (two independent exact paths must agree)."""
    import jax.numpy as jnp

    from knn_tpu.backends.tpu import knn_forward_tiled
    from knn_tpu.utils.padding import pad_axis_to_multiple

    train, test, feats, labels, trials, preds = _scaled_stripe_run(
        reps_tile=325, k=5, block_q=864, block_n=2048, r_lo=2, r_hi=8,
    )
    per_step = _median(trials)
    n = feats.shape[0]
    q = test.num_instances
    qps = q / per_step
    dist_rate = q * n / per_step

    # Exactness cross-check: the XLA tiled running-top-k on the same arrays
    # (independent exact formulation) must predict identically.
    txr, _ = pad_axis_to_multiple(feats, 65536, axis=0)
    tyr, _ = pad_axis_to_multiple(labels, 65536, axis=0)
    qxr, _ = pad_axis_to_multiple(test.features, 128, axis=0)
    want = np.asarray(knn_forward_tiled(
        jnp.asarray(txr), jnp.asarray(tyr), jnp.asarray(qxr),
        jnp.asarray(n, jnp.int32), k=5, num_classes=train.num_classes,
        query_tile=128, train_tile=65536,
    ))[:q]
    exact = bool(np.array_equal(preds, want))
    log(f"stripe vs XLA tiled prediction equality: {exact}")
    return {
        "metric": "xxl_10M_k5_query_throughput",
        "value": round(qps, 1),
        "unit": "queries/sec",
        "vs_baseline": None,
        "train_rows": int(n),
        "dist_evals_per_sec": round(dist_rate / 1e9, 1),
        "dist_unit": "Gdist/s",
        **_spread(trials, digits=2),
        "paths_agree": exact,
    }


def bench_ingest():
    """Ingest throughput: the libarff replacement. The reference parser costs
    one fread call per character (arff_scanner.cpp:46); ours reads the file
    once and emits dense arrays. Reports MB/s and rows/s for the native C++
    parser and the pure-Python fallback."""
    import os

    from knn_tpu.data import pyarff

    train_path = None
    ref = Path("/root/reference/datasets/large-train.arff")
    if ref.exists():
        train_path = str(ref)
    else:
        out = Path(__file__).parent / "build" / "fixtures"
        load_large()  # ensure synth fixtures exist
        train_path = str(out / "large-train.arff")
    size_mb = os.path.getsize(train_path) / 1e6

    def timeit(fn, reps=5):
        trials = []
        rows = 0
        for _ in range(reps):
            t0 = time.monotonic()
            ds = fn()
            trials.append(time.monotonic() - t0)
            rows = ds.num_instances
        return min(trials), rows, trials

    results = {}
    try:
        from knn_tpu.native import arff_native

        # reps=9: the 1-core host is contended right after heavy phases and
        # a 6 ms parse min needs more draws than device-slope configs do.
        t_native, rows, tr = timeit(lambda: arff_native.parse(train_path), reps=9)
        results["native_mb_per_s"] = round(size_mb / t_native, 1)
        results["native_rows_per_s"] = round(rows / t_native)
        results["native_ms_trials"] = [round(t * 1e3, 1) for t in tr]
        log(f"native C++ parser: {t_native*1e3:.1f} ms "
            f"({size_mb/t_native:.0f} MB/s, {rows/t_native:.0f} rows/s)")
    except (ImportError, OSError) as e:
        log(f"native parser unavailable: {e}")

    t_py, rows, tr = timeit(lambda: pyarff.parse_arff_file(train_path), reps=3)
    results["python_mb_per_s"] = round(size_mb / t_py, 1)
    results["python_ms_trials"] = [round(t * 1e3, 1) for t in tr]
    log(f"python parser: {t_py*1e3:.1f} ms ({size_mb/t_py:.0f} MB/s)")

    # Amortized large-file rate: per-call fixed costs (open, ctypes, attr
    # JSON, Dataset construction) are a real fraction of a 1.8 MB parse;
    # a ~90 MB file shows the streaming rate a big ingest actually gets
    # (the r3 framing was "a 100 MB ARFF costs ~1.1 s of host time").
    if "native_mb_per_s" in results:
        big = Path(__file__).parent / "build" / "ingest_xl.arff"
        raw = Path(train_path).read_text()
        head_end = raw.lower().index("@data") + len("@data\n")
        body = raw[head_end:]
        expected = head_end + 50 * len(body)
        if not big.exists() or os.path.getsize(big) != expected:
            # Size-checked against the current source so a regenerated
            # fixture can't leave a stale replica being measured.
            big.parent.mkdir(parents=True, exist_ok=True)
            with open(big, "w") as f:
                f.write(raw[:head_end])
                for _ in range(50):
                    f.write(body)
        big_mb = os.path.getsize(big) / 1e6
        t_big, big_rows, tr = timeit(
            lambda: arff_native.parse(str(big)), reps=3)
        results["native_xl_file_mb"] = round(big_mb, 1)
        results["native_xl_mb_per_s"] = round(big_mb / t_big, 1)
        results["native_xl_ms_trials"] = [round(t * 1e3, 1) for t in tr]
        log(f"native C++ parser, {big_mb:.0f} MB file: {t_big*1e3:.0f} ms "
            f"({big_mb/t_big:.0f} MB/s, {big_rows:,} rows)")

    return {
        "metric": "arff_ingest_throughput",
        "value": results.get("native_mb_per_s", results["python_mb_per_s"]),
        "unit": "MB/s",
        "vs_baseline": None,
        "file_mb": round(size_mb, 2),
        # The r5 parallel @data scan engages at >= 2 cores; this box has
        # one, so these are the serial path's numbers (the parallel path is
        # pinned bit-identical in tests/test_native_parallel.py and scales
        # on real hosts).
        "host_cores": os.cpu_count(),
        **results,
    }


def bench_sharded():
    """The distributed (shard_map) path on one chip: query-sharded over a
    1-device mesh, per-shard candidates from the lane-striped Pallas kernel
    (VERDICT r1 #1 — the mpi.cpp replacement at headline-kernel throughput).
    On a pod the same jitted fn spans the full mesh; per-chip throughput is
    what this measures."""
    import jax
    import jax.numpy as jnp

    from knn_tpu.ops.pallas_knn import (
        stripe_prepare_queries, stripe_prepare_train,
    )
    from knn_tpu.parallel.mesh import make_mesh
    from knn_tpu.parallel.query_sharded import build_query_sharded_stripe_fn
    from knn_tpu.utils.evaluate import accuracy, confusion_matrix

    train, test, is_reference = load_large()
    n, d_true = train.features.shape
    q = test.num_instances
    block_q, block_n = 864, 2048  # headline tuning (1,718 -> 2 blocks of 864)
    txT_h, d_pad = stripe_prepare_train(train.features, block_n)
    from knn_tpu.ops.pallas_knn import stripe_inputs_finite

    mesh = make_mesh(1, axis_names=("q",))
    fn = build_query_sharded_stripe_fn(
        mesh, K, train.num_classes, "exact", block_q, block_n, d_true,
        interpret=False,
        assume_finite=stripe_inputs_finite(train.features, test.features),
    )
    txT = jnp.asarray(txT_h)
    ty = jnp.asarray(np.pad(train.labels, (0, txT_h.shape[1] - n)))
    nv = jnp.asarray(n, jnp.int32)
    bufs = [
        jnp.asarray(stripe_prepare_queries(
            test.features + np.float32(i) * 1e-7, block_q, d_pad))
        for i in range(200)  # one distinct buffer per dispatch (dedupe-proof)
    ]
    jax.block_until_ready(bufs)

    def step(qb):
        return fn(txT, ty, qb, nv)

    t0 = time.monotonic()
    preds = np.asarray(step(bufs[0]))[:q]
    log(f"sharded compile+first run: {time.monotonic() - t0:.2f}s")
    acc = accuracy(confusion_matrix(preds, test.labels, test.num_classes))
    trials = _slope_trials(step, bufs, 50, 200)
    per_step = _median(trials)
    qps = q / per_step
    log(f"sharded (1-dev mesh, stripe engine): {per_step*1e3:.3f} ms/step "
        f"({qps:.0f} q/s), accuracy {acc:.4f}")
    return {
        "metric": "large_k5_sharded_query_throughput",
        "value": round(qps, 1),
        "unit": "queries/sec",
        "vs_baseline": round(qps / BASELINE_QPS, 1),
        "accuracy": round(acc, 4),
        **_spread(trials),
        "mesh": "1-device shard_map, stripe engine",
    }


def bench_kneighbors():
    """Model retrieval API (KNNClassifier.kneighbors) end-to-end wall time
    per call — query padding + transfer + kernel + fetch, with the fitted
    model's Dataset.device_cache keeping the train layout resident — for
    each candidate engine. Proves VERDICT r1 #6: retrieval rides the stripe
    kernel on TPU (engine auto) instead of being pinned to the slower XLA
    scan. Wall numbers include the fixed per-call host sync (~tens of ms on
    a tunneled device), so they are API latencies, not kernel throughput."""
    from knn_tpu.models.knn import KNNClassifier

    train, test, _ = load_large()
    q = test.num_instances
    results = {}
    for engine in ("auto", "xla"):
        model = KNNClassifier(k=K, engine=engine).fit(train)
        model.kneighbors(test)  # warm: compile + populate device cache
        trials = []
        for _ in range(5):
            t0 = time.monotonic()
            model.kneighbors(test)
            trials.append(time.monotonic() - t0)
        results[engine] = trials
        log(f"kneighbors[{engine}]: {min(trials)*1e3:.1f} ms/call "
            f"({q/min(trials):.0f} q/s wall)")

    # Large-query retrieval wall rate (VERDICT r3 #3): ~110k queries through
    # one kneighbors call. The windowed chunked dispatch must keep wall
    # throughput within ~2x of the kernel step rate — at 1,718 queries the
    # fixed ~75 ms tunnel sync IS the wall time, so only a large batch can
    # show whether retrieval pipelines.
    from knn_tpu.data.dataset import Dataset

    big = np.tile(test.features, (64, 1))
    big += 1e-4 * np.random.default_rng(1).standard_normal(
        big.shape, dtype=np.float32)
    big_ds = Dataset(big, np.zeros(len(big), np.int32))
    model = KNNClassifier(k=K, engine="auto").fit(train)
    # Warm with the full set so the executable the trials run is compiled
    # (110k queries fit one chunk at the 128k default cap; the 660k sweep
    # below exercises the chunked path).
    model.kneighbors(big_ds)
    big_trials = []
    for _ in range(3):
        t0 = time.monotonic()
        model.kneighbors(big_ds)
        big_trials.append(time.monotonic() - t0)
    big_q = big.shape[0]
    big_qps = big_q / min(big_trials)
    log(f"kneighbors[auto] {big_q:,} queries: {min(big_trials)*1e3:.0f} ms "
        f"({big_qps:,.0f} q/s wall)")

    # 6x larger sweep, where the fixed ~100 ms tunnel sync amortizes, plus
    # the wall decomposition the number depends on: after any executable
    # has run, the axon tunnel moves large host->device payloads at a
    # phase-dependent 20 MB/s-1.5 GB/s (r5 probe) — the query upload, not
    # the kernel, is the large-Q ceiling on bad days. upload_ms measures a
    # bare same-payload transfer in this session so the artifact separates
    # tunnel bandwidth from compute.
    import jax as _jax
    import jax.numpy as _jnp

    huge = np.tile(test.features, (384, 1))
    huge += 1e-4 * np.random.default_rng(2).standard_normal(
        huge.shape, dtype=np.float32)
    huge_ds = Dataset(huge, np.zeros(len(huge), np.int32))
    model.kneighbors(huge_ds)  # warm
    huge_trials = []
    for _ in range(5):  # wall is upload-phase-dependent; give the min a shot
        t0 = time.monotonic()
        model.kneighbors(huge_ds)
        huge_trials.append(time.monotonic() - t0)
    huge_q = huge.shape[0]
    huge_qps = huge_q / min(huge_trials)
    up_probe = _jnp.asarray(huge)
    _jax.block_until_ready(up_probe)  # first transfer warms the shape path
    huge_shifted = huge + np.float32(1.0)  # distinct content, built off-clock
    t0 = time.monotonic()
    up_probe2 = _jnp.asarray(huge_shifted)
    _jax.block_until_ready(up_probe2)
    upload_ms = (time.monotonic() - t0) * 1e3
    del up_probe, up_probe2, huge_shifted
    upload_mb = huge.nbytes / 1e6
    log(f"kneighbors[auto] {huge_q:,} queries: {min(huge_trials)*1e3:.0f} ms "
        f"({huge_qps:,.0f} q/s wall; bare {upload_mb:.0f} MB upload "
        f"{upload_ms:.0f} ms this session)")

    # Amortized interactive latency (VERDICT r4 #6): M default-shape calls
    # through the async surface, resolved together, pay ~one ~100 ms tunnel
    # sync instead of M. The sync==async equality is pinned in
    # tests/test_async_api.py; here we measure the per-call wall cost.
    model_async = KNNClassifier(k=K, engine="auto").fit(train)
    model_async.kneighbors(test)  # warm compile + device cache
    m_calls = 10
    pipelined_trials = []
    for _ in range(3):
        t0 = time.monotonic()
        handles = [model_async.kneighbors_async(test) for _ in range(m_calls)]
        for h in handles:
            h.result()
        pipelined_trials.append((time.monotonic() - t0) / m_calls)
    log(f"kneighbors_async x{m_calls}: {_median(pipelined_trials)*1e3:.1f} "
        f"ms/call median (vs {min(results['auto'])*1e3:.1f} sync)")
    return {
        "metric": "large_k5_kneighbors_wall_throughput",
        "value": round(q / min(results["auto"]), 1),
        "unit": "queries/sec",
        "vs_baseline": None,
        "auto_ms_per_call": round(min(results["auto"]) * 1e3, 1),
        "auto_ms_trials": [round(t * 1e3, 1) for t in results["auto"]],
        "xla_ms_per_call": round(min(results["xla"]) * 1e3, 1),
        "xla_ms_trials": [round(t * 1e3, 1) for t in results["xla"]],
        "large_q": big_q,
        "large_q_qps": round(big_qps, 1),
        "large_q_ms_trials": [round(t * 1e3, 1) for t in big_trials],
        "huge_q": huge_q,
        "huge_q_qps": round(huge_qps, 1),
        "huge_q_ms_trials": [round(t * 1e3, 1) for t in huge_trials],
        "upload_mb": round(upload_mb, 1),
        "upload_ms": round(upload_ms, 1),
        "pipelined_ms_per_call": round(_median(pipelined_trials) * 1e3, 2),
        "pipelined_ms_trials": [round(t * 1e3, 2) for t in pipelined_trials],
        "pipelined_calls": m_calls,
    }


def bench_sweepk():
    """VERDICT r3 #7: the measured version of the sweep_k claim — every k in
    {1, 5, 10} from ONE shared retrieval should cost about one max-k run,
    where the reference re-runs the whole binary per k (BASELINE.json runs
    them as separate jobs). Measured at two scales: the headline train set
    and the xl 1M-row tiling, both through the real model API (device cache
    warm, compiles warm)."""
    from knn_tpu.data.dataset import Dataset
    from knn_tpu.models.knn import sweep_k
    from knn_tpu.utils.evaluate import accuracy, confusion_matrix

    train, test, is_reference = load_large()
    ks = [1, 5, 10]
    record = {
        "metric": "sweepk_vs_single_cost",
        "value": None,  # filled with the large-config ratio below
        "unit": "sweep_wall / single_k10_wall",
        "vs_baseline": None,
    }

    xl_ds = Dataset(*_tiled_large(train, 33))

    for name, tr_ds in (("large", train), ("xl_1M", xl_ds)):
        preds = sweep_k(tr_ds, test, ks)  # warm: compile + device cache
        if name == "large" and is_reference:
            accs = {
                k: round(accuracy(confusion_matrix(
                    preds[k], test.labels, test.num_classes)), 4)
                for k in ks
            }
            log(f"sweep_k accuracies: {accs} "
                f"(golden 0.9919 / 0.9948 / 0.7538)")
            record["large_accuracies"] = accs
        sweep_trials, single_trials, kmax_trials = [], [], []
        for _ in range(3):
            t0 = time.monotonic()
            sweep_k(tr_ds, test, ks)
            sweep_trials.append(time.monotonic() - t0)
        for k in ks:
            # Warm each k's single-run shape — and use the output to verify
            # the prefix-equivalence claim itself: every sweep entry must
            # equal that k's individual run.
            single = sweep_k(tr_ds, test, [k])
            if not np.array_equal(preds[k], single[k]):
                log(f"WARNING: sweep_k[{name}] k={k} diverges from the "
                    f"individual run — prefix-vote invariant broken")
                record["prefix_equivalence"] = False
        record.setdefault("prefix_equivalence", True)
        for _ in range(3):
            t0 = time.monotonic()
            for k in ks:
                sweep_k(tr_ds, test, [k])
            single_trials.append(time.monotonic() - t0)
            t0 = time.monotonic()
            sweep_k(tr_ds, test, [ks[-1]])
            kmax_trials.append(time.monotonic() - t0)
        t_sweep, t_three = min(sweep_trials), min(single_trials)
        t_kmax = min(kmax_trials)
        log(f"sweep_k[{name}]: sweep {t_sweep*1e3:.0f} ms vs three runs "
            f"{t_three*1e3:.0f} ms vs one k=10 run {t_kmax*1e3:.0f} ms")
        record[f"{name}_sweep_ms"] = round(t_sweep * 1e3, 1)
        record[f"{name}_three_runs_ms"] = round(t_three * 1e3, 1)
        record[f"{name}_single_k10_ms"] = round(t_kmax * 1e3, 1)
        record[f"{name}_sweep_ms_trials"] = [
            round(t * 1e3, 1) for t in sweep_trials
        ]
        record[f"{name}_single_k10_ms_trials"] = [
            round(t * 1e3, 1) for t in kmax_trials
        ]
        if name == "large":
            record["value"] = round(t_sweep / t_kmax, 2)
    return record


def bench_headline():
    import jax
    import jax.numpy as jnp

    from knn_tpu.backends.tpu import knn_forward
    from knn_tpu.ops.pallas_knn import knn_stripe_classify
    from knn_tpu.utils.evaluate import confusion_matrix, accuracy

    t0 = time.monotonic()
    train, test, is_reference = load_large()
    log(f"loaded datasets in {time.monotonic() - t0:.1f}s "
        f"(train {train.features.shape}, test {test.features.shape}, "
        f"reference={is_reference})")
    dev = jax.devices()[0]
    log(f"device: {dev.device_kind} ({dev.platform})")

    train_x = jax.device_put(jnp.asarray(train.features), dev)
    train_y = jax.device_put(jnp.asarray(train.labels), dev)
    test_x = jax.device_put(jnp.asarray(test.features), dev)
    nc = train.num_classes

    # Headline exact path: the lane-striped Pallas kernel (one fused dispatch).
    from knn_tpu.ops.pallas_knn import (
        stripe_inputs_finite, stripe_prepare_train, stripe_prepare_queries,
    )

    n, d_true = train.features.shape
    # 1,718 queries -> 2 blocks of 864 (0.6% padding); 896 was the r1 tuning
    # but the lite selection rounds shift Mosaic's stack allocation ~0.5 MB
    # past the 16 MB VMEM budget at that size.
    block_q, block_n = 864, 2048
    finite = stripe_inputs_finite(train.features, test.features)
    txT_host, d_pad = stripe_prepare_train(train.features, block_n)
    txT = jax.device_put(jnp.asarray(txT_host), dev)
    nv = jnp.asarray(n, jnp.int32)

    def pad_queries(arr):
        return stripe_prepare_queries(arr, block_q, d_pad)

    def step(q):
        return knn_stripe_classify(
            txT, train_y, q, nv, k=K, num_classes=nc,
            block_q=block_q, block_n=block_n, d_true=d_true,
            assume_finite=finite,
        )

    test_x_padded = jax.device_put(jnp.asarray(pad_queries(test.features)), dev)

    # Warmup / compile.
    t0 = time.monotonic()
    preds = np.asarray(step(test_x_padded))[: test.num_instances]
    log(f"compile+first run: {time.monotonic() - t0:.2f}s")

    acc = accuracy(confusion_matrix(preds, test.labels, test.num_classes))
    log(f"accuracy: {acc:.4f} (golden {GOLDEN_ACC})")
    if is_reference and round(acc, 4) != GOLDEN_ACC:
        log("WARNING: accuracy does not match the reference golden value")

    # Steady-state throughput. Per-call host sync here costs a fixed ~75 ms
    # tunnel round-trip that has nothing to do with device compute (a jitted
    # scalar add measures the same), so time a pipelined batch of dispatches
    # with one final sync and take the slope between two batch sizes — the
    # marginal per-step device time. Each dispatch uses a different query
    # buffer so no layer can dedupe repeated identical executions.
    qbufs = [
        jax.device_put(
            jnp.asarray(pad_queries(test.features + np.float32(i) * 1e-7)), dev
        )
        for i in range(200)  # one distinct buffer per dispatch (dedupe-proof)
    ]
    # Unpadded variants for the XLA-formulation diagnostics (knn_forward needs
    # no query padding; timing it on padded rows would bias the comparison).
    qbufs_raw = [
        jax.device_put(jnp.asarray(test.features + np.float32(i) * 1e-7), dev)
        for i in range(200)
    ]
    jax.block_until_ready(qbufs + qbufs_raw)

    trials = _slope_trials(step, qbufs, 50, 200)
    per_step = _median(trials)
    qps = test.num_instances / per_step
    log(f"pipelined slope: {per_step*1e3:.3f} ms/step median of {len(trials)} "
        f"(trials: {[round(t*1e3, 3) for t in trials]})")

    # Diagnostic: the plain XLA full-matrix formulation (previous headline).
    def step_full(q):
        return knn_forward(train_x, train_y, q, k=K, num_classes=nc)

    np.asarray(step_full(qbufs_raw[0]))
    full_step, _ = _pipelined_slope(step_full, qbufs_raw, 50, 200)
    log(f"xla full-matrix exact: {full_step*1e3:.3f} ms/step "
        f"({test.num_instances/full_step:.0f} q/s)")

    # Secondary: TPU hardware approximate top-k (opt-in mode, not
    # prediction-exact; on this dataset it happens to keep the golden
    # accuracy).
    def step_approx(q):
        return knn_forward(train_x, train_y, q, k=K, num_classes=nc, approx=True)

    approx_acc = accuracy(confusion_matrix(
        np.asarray(step_approx(test_x)), test.labels, test.num_classes))
    approx_step, _ = _pipelined_slope(step_approx, qbufs_raw, 50, 200)
    approx_qps = test.num_instances / approx_step
    log(f"approx top-k: {approx_step*1e3:.3f} ms/step "
        f"({approx_qps:.0f} q/s), accuracy {approx_acc:.4f}")

    return {
        "metric": "large_k5_query_throughput",
        "value": round(qps, 1),
        "unit": "queries/sec",
        "vs_baseline": round(qps / BASELINE_QPS, 1),
        "accuracy": round(acc, 4),
        **_spread(trials),
        "approx_topk_qps": round(approx_qps, 1),
        "approx_topk_accuracy": round(approx_acc, 4),
    }


def _load_medium():
    """The medium preset (serving's load dataset — big enough to make a
    dispatch cost something, small enough that closed-loop trials finish
    in seconds)."""
    from knn_tpu.data.arff import load_arff

    ref = Path("/root/reference/datasets")
    if ref.exists():
        d = ref
    else:
        load_large()  # generates the full synthetic fixture ladder
        d = Path(__file__).parent / "build" / "fixtures"
    return (
        load_arff(str(d / "medium-train.arff")),
        load_arff(str(d / "medium-test.arff")),
    )


#: The bucket ladder the serving bench (and the bench gate's serving
#: trials) dispatch under: single-row closed-loop clients form small
#: batches, so the ladder starts low — the shape an operator would pick
#: after reading /debug/capacity's waste numbers for this traffic
#: (docs/SERVING.md §Tuning the bucket ladder).
SERVE_BENCH_BUCKETS = (4, 8, 16, 32, 64)


def bench_serving():
    from knn_tpu.models.knn import query_bucket_ladder

    with query_bucket_ladder(SERVE_BENCH_BUCKETS):
        return _bench_serving_body()


def _bench_serving_body():
    """The serving subsystem's claim, measured (docs/SERVING.md): under
    concurrent closed-loop load, the micro-batcher's coalesced dispatch
    beats naive sequential per-call dispatch on per-request p50 latency
    once concurrency covers the coalescing window (acceptance: c >= 8 on
    the medium preset). Both modes run the SAME engine path (kneighbors +
    host vote) so the delta is pure batching, not code-path skew.

    Sequential baseline = the same FIFO queue with batching pinned OFF
    (max_batch=1, no wait window): one engine dispatch per request in
    arrival order — what a naive single-worker server does. Same queue
    discipline, same code path; the only delta is the coalescing policy.
    (A bare lock instead would measure Python lock barging: unfairly
    scheduled threads produce a great p50 and a ~1 s p99 — observed on
    the 1-core bench box — which flatters the baseline's median while its
    throughput collapses.) Self-diagnosis counters (dropped/deadline-
    expired, the PR 1 dropped-trial pattern) ride the record so a load
    artifact that silently shed requests cannot read as a clean run."""
    import threading

    from knn_tpu import obs
    from knn_tpu.data.dataset import Dataset
    from knn_tpu.models.knn import KNNClassifier
    from knn_tpu.serve.artifact import warmup
    from knn_tpu.serve.batcher import MicroBatcher

    obs_was = obs.enabled()
    obs.enable()
    train, test = _load_medium()
    q = test.num_instances
    model = KNNClassifier(k=K, engine="auto").fit(train)
    # Bucketed serving: every ladder bucket is its own compiled
    # executable, so warmup sweeps the whole ladder (the serve boot's
    # rule) — trials then measure dispatch, never compilation.
    log(f"serving preset: {train.num_instances} train rows x "
        f"{train.num_features} features; buckets {SERVE_BENCH_BUCKETS}; "
        f"warm {warmup(model, (1,) + SERVE_BENCH_BUCKETS)}")

    MAX_BATCH, MAX_WAIT_MS, REQS = 64, 2.0, 30
    levels = (1, 4, 8, 16)

    def closed_loop(concurrency, request_fn):
        """``concurrency`` clients x ``REQS`` single-row requests each;
        returns (sorted per-request latencies s, wall s)."""
        lats, errors = [], []
        lock = threading.Lock()

        def client(cid):
            mine = []
            for i in range(REQS):
                row = test.features[(cid * REQS + i) % q]
                t0 = time.monotonic()
                try:
                    request_fn(row)
                except Exception as e:  # noqa: BLE001 — recorded, reported
                    errors.append(f"{type(e).__name__}: {e}")
                    continue
                mine.append(time.monotonic() - t0)
            with lock:
                lats.extend(mine)

        threads = [threading.Thread(target=client, args=(c,))
                   for c in range(concurrency)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
        if errors:
            log(f"serving: {len(errors)} failed requests, first: {errors[0]}")
        return sorted(lats), wall, len(errors)

    from knn_tpu.obs.instrument import SERVE_BATCH_BUCKETS

    def batch_hist():
        # Buckets must match the batcher's registration or get-or-create
        # raises a conflicting-ladder error.
        return obs.registry().histogram("knn_serve_batch_size",
                                        buckets=SERVE_BATCH_BUCKETS)

    def batch_stats_delta(before):
        h = batch_hist()
        d_count, d_sum = h.count - before[0], h.sum - before[1]
        return (h.count, h.sum), (d_sum / d_count if d_count else 0.0)

    record = {
        "metric": "serving_c8_batched_p50_ms",
        "value": None,
        "unit": "ms",
        "vs_baseline": None,
        "train_rows": train.num_instances,
        "max_batch": MAX_BATCH,
        "max_wait_ms": MAX_WAIT_MS,
        "batch_buckets": list(SERVE_BENCH_BUCKETS),
        "requests_per_client": REQS,
        "levels": {},
    }
    failed = 0
    for conc in levels:
        total = conc * REQS
        batcher = MicroBatcher(model, max_batch=MAX_BATCH,
                               max_wait_ms=MAX_WAIT_MS,
                               buckets=SERVE_BENCH_BUCKETS)
        try:
            before = (batch_hist().count, batch_hist().sum)
            b_lats, b_wall, b_err = closed_loop(
                conc, lambda row: batcher.predict(row, timeout=120))
            before, mean_batch = batch_stats_delta(before)
        finally:
            batcher.close()
        # The sequential baseline: same queue, coalescing pinned off.
        seq = MicroBatcher(model, max_batch=1, max_wait_ms=0.0)
        try:
            s_lats, s_wall, s_err = closed_loop(
                conc, lambda row: seq.predict(row, timeout=120))
        finally:
            seq.close()
        failed += b_err + s_err

        def pct(lats, p):
            return round(float(np.percentile(lats, p)) * 1e3, 2) if lats else None

        row = {
            "batched_p50_ms": pct(b_lats, 50),
            "batched_p99_ms": pct(b_lats, 99),
            "batched_qps": round((total - b_err) / b_wall, 1),
            "seq_p50_ms": pct(s_lats, 50),
            "seq_p99_ms": pct(s_lats, 99),
            "seq_qps": round((total - s_err) / s_wall, 1),
            "mean_batch_requests": round(mean_batch, 2),
        }
        record["levels"][str(conc)] = row
        log(f"serving c={conc}: batched p50 {row['batched_p50_ms']} ms / "
            f"p99 {row['batched_p99_ms']} ms / {row['batched_qps']} q/s "
            f"(mean batch {row['mean_batch_requests']}) vs sequential p50 "
            f"{row['seq_p50_ms']} ms / {row['seq_qps']} q/s")

    c8 = record["levels"]["8"]
    record["value"] = c8["batched_p50_ms"]
    record.update(
        c8_batched_p50_ms=c8["batched_p50_ms"],
        c8_seq_p50_ms=c8["seq_p50_ms"],
        c8_batched_qps=c8["batched_qps"],
        c8_seq_qps=c8["seq_qps"],
        batched_beats_seq_c8=bool(
            c8["batched_p50_ms"] is not None and c8["seq_p50_ms"] is not None
            and c8["batched_p50_ms"] < c8["seq_p50_ms"]
        ),
    )

    # Request-tracing cost, measured: the same c=8 batched run with a
    # flight recorder attached — every request then owns a full timeline
    # (phases, attempts, recorder commit). The delta vs c8_batched_p50_ms
    # is the per-request price of `--flight-recorder-size` (expected:
    # in run-to-run noise; docs/OBSERVABILITY.md §Overhead).
    from knn_tpu.obs.reqtrace import FlightRecorder

    rec = FlightRecorder(capacity=1024, slowest_k=16)
    traced = MicroBatcher(model, max_batch=MAX_BATCH,
                          max_wait_ms=MAX_WAIT_MS, recorder=rec,
                          buckets=SERVE_BENCH_BUCKETS)
    try:
        t_lats, t_wall, t_err = closed_loop(
            8, lambda row: traced.predict(row, timeout=120))
    finally:
        traced.close()
    failed += t_err
    record["c8_traced_p50_ms"] = pct(t_lats, 50)
    record["c8_traced_qps"] = round((8 * REQS - t_err) / t_wall, 1)
    record["traced_timelines"] = rec.stats()["completed"]
    log(f"serving c=8 with request tracing: p50 "
        f"{record['c8_traced_p50_ms']} ms ({record['c8_traced_qps']} q/s, "
        f"{record['traced_timelines']} timelines recorded) vs untraced "
        f"{c8['batched_p50_ms']} ms")
    # Shadow-scoring cost, measured (the PR 7 acceptance): the same c=8
    # batched run with a ShadowScorer at --shadow-rate 0.1 — each sampled
    # request is re-answered on the oracle rung by a BACKGROUND worker
    # while the batcher's tap is one RNG draw + one bounded append. The
    # delta vs c8_batched_p50_ms must sit inside the closed-loop noise
    # (the provably-never-blocks contract; a full queue sheds, recorded
    # below so a shedding run can't read as a cheap one).
    from knn_tpu.obs.quality import ShadowScorer

    shadow = ShadowScorer(0.1, queue_cap=1024, seed=0)
    shadowed = MicroBatcher(model, max_batch=MAX_BATCH,
                            max_wait_ms=MAX_WAIT_MS, quality=shadow,
                            buckets=SERVE_BENCH_BUCKETS)
    try:
        sh_lats, sh_wall, sh_err = closed_loop(
            8, lambda row: shadowed.predict(row, timeout=120))
        shadow.drain(60)
    finally:
        shadowed.close()
        shadow.close()
    failed += sh_err
    sh_summary = shadow.export()
    record["c8_shadow_p50_ms"] = pct(sh_lats, 50)
    record["c8_shadow_qps"] = round((8 * REQS - sh_err) / sh_wall, 1)
    record["shadow_scored"] = sh_summary["scored"]
    record["shadow_shed"] = sh_summary["shed"]
    record["shadow_recall"] = (
        sh_summary["rungs"].get("fast", {}).get("recall")
        if sh_summary["rungs"] else None
    )
    log(f"serving c=8 with shadow scoring (rate 0.1): p50 "
        f"{record['c8_shadow_p50_ms']} ms ({record['c8_shadow_qps']} q/s, "
        f"{record['shadow_scored']} scored / {record['shadow_shed']} shed, "
        f"recall {record['shadow_recall']}) vs shadow-off "
        f"{c8['batched_p50_ms']} ms")
    # Cost & capacity telemetry, measured (the PR 8 acceptance): the same
    # c=8 batched run with the accounting + capacity layers attached —
    # occupancy says how full the compiled batch shape ran, waste ratio
    # what the shape quantum padded on top, duty cycle how busy the worker
    # was at this load. The p50 delta vs the bare batched run is the
    # layers' per-request price (expected: inside closed-loop noise).
    from knn_tpu.obs.accounting import CostAccountant
    from knn_tpu.obs.capacity import CapacityTracker

    accountant = CostAccountant()
    capacity = CapacityTracker(MAX_BATCH, window_s=300)
    costed = MicroBatcher(model, max_batch=MAX_BATCH,
                          max_wait_ms=MAX_WAIT_MS, accounting=accountant,
                          capacity=capacity, buckets=SERVE_BENCH_BUCKETS)
    try:
        cc_lats, cc_wall, cc_err = closed_loop(
            8, lambda row: costed.predict(row, timeout=120))
    finally:
        costed.close()
    failed += cc_err
    cap_doc = capacity.export()
    cost_totals = accountant.export()["totals"]
    record["c8_cost_p50_ms"] = pct(cc_lats, 50)
    record["c8_occupancy_mean"] = cap_doc["occupancy_mean"]
    record["c8_padded_row_waste_ratio"] = cap_doc["padded_row_waste_ratio"]
    record["c8_duty_cycle"] = cap_doc["duty_cycle"]
    record["cost_conservation_ok"] = bool(
        abs(cost_totals["attributed_ms"] - cost_totals["dispatch_wall_ms"])
        <= 1e-6 * max(1.0, cost_totals["dispatch_wall_ms"])
    )
    log(f"serving c=8 with cost accounting: p50 "
        f"{record['c8_cost_p50_ms']} ms vs bare {c8['batched_p50_ms']} ms; "
        f"occupancy {record['c8_occupancy_mean']}, padded-row waste "
        f"{record['c8_padded_row_waste_ratio']}, duty cycle "
        f"{record['c8_duty_cycle']}, conservation "
        f"{record['cost_conservation_ok']} "
        f"({cost_totals['attributed_ms']:.3f} of "
        f"{cost_totals['dispatch_wall_ms']:.3f} ms attributed)")
    # Self-diagnosis: shed load must be visible in the artifact.
    reg = obs.registry()
    record["dropped_requests"] = sum(
        i.value for i in reg.instruments()
        if i.name == "knn_serve_rejected_total"
    )
    record["deadline_expired"] = sum(
        i.value for i in reg.instruments()
        if i.name == "knn_serve_deadline_expired_total"
    )
    record["failed_requests"] = failed
    if not obs_was:
        obs.disable()
    return record


def bench_replay():
    """The workload-replay subsystem's claim, measured
    (docs/OBSERVABILITY.md §Workload capture & replay): a committed
    fixture workload (tests/data/replay-workload — 120 seeded bursty
    reads over ~2 s) re-drives open-loop against an in-process batcher
    with its original inter-arrival timing, and the what-if simulator's
    predicted p50 for the captured policy lands near the measured one.

    Digest verification runs in mode 'always' (the fixture's model is
    REBUILT from the pinned seed, so its version tag cannot match);
    divergences are a REPORTED number, not a failure — the fixture's
    digests are environment-pinned like BENCH_GATE_BASELINE.json, and
    the strict zero-divergence assertion lives in `make replay-gate`,
    which captures and replays within one process."""
    from tests import fixtures
    from knn_tpu.obs import whatif
    from knn_tpu.obs.capacity import CapacityTracker
    from knn_tpu.obs.replay import replay_workload
    from knn_tpu.obs.workload import load_workload
    from knn_tpu.serve.artifact import warmup
    from knn_tpu.serve.batcher import MicroBatcher

    wl = load_workload(fixtures.REPLAY_WORKLOAD_DIR)
    policy = wl.manifest["policy"]
    model = fixtures.replay_fixture_model()
    log(f"replay fixture: {wl.manifest['requests']} requests / "
        f"{wl.manifest['total_rows']} rows over "
        f"{wl.manifest['duration_ms']:.0f} ms, policy {policy}")
    warmup(model, batch_sizes=(1, policy["max_batch"]), kinds=("predict",))

    def run(speed):
        capacity = CapacityTracker(policy["max_batch"])
        batcher = MicroBatcher(
            model, max_batch=policy["max_batch"],
            max_wait_ms=policy["max_wait_ms"],
            index_version=fixtures.REPLAY_FIXTURE_VERSION,
            capacity=capacity,
        )
        try:
            v = replay_workload(wl, batcher=batcher, speed=speed,
                                verify="always")
        finally:
            batcher.close()
        return v, capacity.export()

    paced, cap_doc = run(speed=1.0)
    fast, _ = run(speed=0.0)
    m = paced["measured"]
    fit = cap_doc["dispatch_model"]
    sim = None
    if fit["a_ms"] is not None:
        sim = whatif.simulate(
            wl.arrivals(), max_batch=policy["max_batch"],
            max_wait_ms=policy["max_wait_ms"], a_ms=fit["a_ms"],
            b_ms_per_row=fit["b_ms_per_row"],
        )
    record = {
        "metric": "replay_paced_p50_ms",
        "value": m["p50_ms"],
        "unit": "ms",
        "vs_baseline": None,
        "requests": m["requests"],
        "replay_p50_ms": m["p50_ms"],
        "replay_p99_ms": m["p99_ms"],
        "replay_qps": m["qps"],
        "replay_errors": m["errors"],
        "captured_p50_ms": paced["captured"]["p50_ms"],
        "unpaced_qps": fast["measured"]["qps"],
        "verified": paced["verify"]["verified"],
        "divergences": paced["verify"]["divergences"],
        "occupancy_mean": cap_doc["occupancy_mean"],
        "whatif_p50_ms": sim["p50_ms"] if sim else None,
        "whatif_abs_err_ms": (round(abs(sim["p50_ms"] - m["p50_ms"]), 3)
                              if sim and m["p50_ms"] is not None else None),
        "dispatch_fit": fit,
    }
    log(f"replay paced: p50 {m['p50_ms']} ms / p99 {m['p99_ms']} ms "
        f"({m['qps']} q/s) vs captured p50 "
        f"{record['captured_p50_ms']} ms; unpaced {record['unpaced_qps']} "
        f"q/s; verified {record['verified']}, divergences "
        f"{record['divergences']}; what-if p50 {record['whatif_p50_ms']} "
        f"ms (|err| {record['whatif_abs_err_ms']} ms)")
    return record


def bench_ivf():
    """The IVF index family's claim, measured (docs/INDEXES.md): probed
    approximate retrieval makes the SERVING dispatch sub-linear in index
    size — q/s multiples of the exact fast rung at a measured, tie-aware
    recall@k — on the medium/large fixtures with an nprobe sweep.

    Both sides dispatch at the SERVING batch shape (16-row chunks — the
    micro-batcher's coalesced batches, where the XLA rung pads queries to
    its 128-row quantum and scans every train row regardless): that is
    the rung this index family ships as, and the regime the ivf-soak
    acceptance (>= 3x at recall >= 0.95) is held in. The full-test-set
    one-shot wall rides the record too (``exact_batch_qps``) so the other
    end of the trade — XLA amortizing one huge dispatch — stays visible.
    Recall is scored by the shadow scorer's own ``recall_at_k`` (ties to
    the oracle's k-th distance never count as losses), so these are the
    same quantities the serving SLI enforces. Headline value: the large
    fixture's serving-shape q/s multiple at the first swept nprobe whose
    recall meets 0.95."""
    from knn_tpu.data.dataset import Dataset
    from knn_tpu.index.ivf import IVFIndex
    from knn_tpu.models.knn import KNNClassifier
    from knn_tpu.obs.quality import recall_at_k

    record = {
        "metric": "ivf_large_speedup_at_recall95",
        "value": None,
        "unit": "x",
        "vs_baseline": None,
        "recall_floor": 0.95,
        "dispatch_rows": 16,
        "fixtures": {},
    }
    rows = 16
    cases = {"medium": (_load_medium, 64), "large": (
        lambda: load_large()[:2], 128)}
    for name, (loader, cells) in cases.items():
        train, test = loader()
        q = test.num_instances
        model = KNNClassifier(k=K, engine="auto").fit(train)
        exact_d, exact_i = model.kneighbors(test)  # warm + recall truth

        def serve_shape_wall(dispatch, reps=3):
            """Best-of wall (s) sweeping the whole test set in
            serving-shape chunks."""
            best = None
            for _ in range(reps):
                t0 = time.monotonic()
                for s in range(0, q, rows):
                    dispatch(test.features[s:s + rows])
                best = (time.monotonic() - t0 if best is None
                        else min(best, time.monotonic() - t0))
            return best

        def exact_dispatch(feats):
            model.kneighbors(Dataset(
                feats, np.zeros(feats.shape[0], np.int32)))

        exact_dispatch(test.features[:rows])  # warm the padded shape
        exact_qps = round(q / serve_shape_wall(exact_dispatch), 1)
        t0 = time.monotonic()
        ivf = IVFIndex.build(train.features, cells, seed=0)
        build_ms = round((time.monotonic() - t0) * 1e3, 1)
        t0 = time.monotonic()
        model.kneighbors(test)
        batch_qps = round(q / (time.monotonic() - t0), 1)

        # Host vs device candidate scorer (PR 13, ROADMAP item 2): the
        # SAME coverage/probe set scored by the numpy gather+einsum vs
        # the fused device segment kernel + exact re-rank — one-shot
        # full-test-set dispatch at a mid-sweep nprobe, best-of walls
        # after a warm pass (compiles excluded), with Gdist/s =
        # candidate distances evaluated per second.
        scorer_np = min(8, cells)
        d_feat = train.num_features

        def scorer_wall(mode, reps=3):
            ivf.search(train.features, test.features, K, scorer_np,
                       scorer=mode)  # warm (compile + operand upload)
            best, stats = None, None
            for _ in range(reps):
                t0 = time.monotonic()
                _, _, stats = ivf.search(
                    train.features, test.features, K, scorer_np,
                    scorer=mode)
                wall = time.monotonic() - t0
                best = wall if best is None else min(best, wall)
            return best, stats

        host_wall, host_stats = scorer_wall("host")
        dev_wall, dev_stats = scorer_wall("device")
        scorer_row = {
            "nprobe": scorer_np,
            "host_wall_ms": round(host_wall * 1e3, 2),
            "device_wall_ms": round(dev_wall * 1e3, 2),
            "host_gdist_s": round(
                host_stats.candidate_rows * d_feat / host_wall / 1e9, 4),
            "device_gdist_s": round(
                dev_stats.candidate_rows * d_feat / dev_wall / 1e9, 4),
            "device_speedup": round(host_wall / dev_wall, 2),
            "device_padded_candidate_rows":
                dev_stats.padded_candidate_rows,
        }
        log(f"ivf[{name}] scorer host {scorer_row['host_wall_ms']} ms "
            f"({scorer_row['host_gdist_s']} Gdist/s) vs device "
            f"{scorer_row['device_wall_ms']} ms "
            f"({scorer_row['device_gdist_s']} Gdist/s) — "
            f"{scorer_row['device_speedup']}x")
        row = {
            "train_rows": train.num_instances,
            "queries": q,
            "cells": cells,
            "build_ms": build_ms,
            "cell_imbalance": ivf.imbalance(),
            "exact_qps": exact_qps,
            "exact_batch_qps": batch_qps,
            "scorer": scorer_row,
            "sweep": {},
        }
        speedup_at_floor = recall_at_floor = None
        for nprobe in (1, 2, 4, 8, 16, 32):
            if nprobe > cells:
                break
            wall = serve_shape_wall(
                lambda feats: ivf.search(train.features, feats, K, nprobe))
            qps = round(q / wall, 1)
            d, i, stats = ivf.search(
                train.features, test.features, K, nprobe)
            recall = round(float(recall_at_k(
                i, exact_i, exact_d.astype(np.float64),
                d.astype(np.float64)).mean()), 4)
            scanned = round(stats.candidate_rows
                            / (q * train.num_instances), 4)
            row["sweep"][str(nprobe)] = {
                "qps": qps, "recall": recall,
                "speedup": round(qps / exact_qps, 2),
                "scanned_fraction": scanned,
            }
            log(f"ivf[{name}] nprobe={nprobe}: {qps} q/s at serving "
                f"shape ({row['sweep'][str(nprobe)]['speedup']}x exact "
                f"{exact_qps}), recall {recall}, scanned {scanned}")
            if speedup_at_floor is None and recall >= 0.95:
                speedup_at_floor = round(qps / exact_qps, 2)
                recall_at_floor = recall
                row["nprobe_at_floor"] = nprobe
        row["speedup_at_recall95"] = speedup_at_floor
        row["recall_at_floor"] = recall_at_floor
        record["fixtures"][name] = row
    lg = record["fixtures"]["large"]
    record["value"] = lg["speedup_at_recall95"]
    record.update(
        large_speedup_at_recall95=lg["speedup_at_recall95"],
        large_recall=lg["recall_at_floor"],
        large_nprobe=lg.get("nprobe_at_floor"),
        large_exact_qps=lg["exact_qps"],
        medium_speedup_at_recall95=(
            record["fixtures"]["medium"]["speedup_at_recall95"]),
        large_device_scorer_speedup=lg["scorer"]["device_speedup"],
        large_device_gdist_s=lg["scorer"]["device_gdist_s"],
        large_host_gdist_s=lg["scorer"]["host_gdist_s"],
    )
    return record


def bench_gate_config(serving_trials=3, predict_reps=7):
    """The perf-regression gate's record (`make bench-gate`,
    scripts/bench_gate.py): a minutes-scale, CPU-runnable subset of the
    bench surface whose every metric is a TRIAL LIST, so obs/regress.py
    can apply the best-of-mins + MAD-tolerance rule. Three layers, one
    metric each:

    - ``predict_wall_ms``  — medium-preset warm predict wall (the kernel +
      dispatch path the disabled-overhead gate also watches);
    - ``kneighbors_wall_ms`` — the retrieval API wall (what serving
      dispatches ride);
    - ``serve_c8_p50_ms``  — micro-batched closed-loop p50 at c=8 (the
      serving hot path), one p50 per repeat so batching-policy regressions
      gate too;
    - ``ingest_ms``        — the ARFF parse (native parser when built,
      labeled which).

    NOT the full bench: the device-bound configs (mnist/xl/xxl) need the
    real chip and hours; this gate is the tripwire that runs everywhere.
    """
    import threading

    from knn_tpu.data import pyarff
    from knn_tpu.models.knn import KNNClassifier
    from knn_tpu.serve.batcher import MicroBatcher

    train, test = _load_medium()
    model = KNNClassifier(k=K, engine="auto").fit(train)
    model.predict(test)  # warm: compile + device cache
    predict_trials = []
    for _ in range(predict_reps):
        t0 = time.monotonic()
        model.predict(test)
        predict_trials.append(round((time.monotonic() - t0) * 1e3, 3))
    log(f"gate predict: best {min(predict_trials)} ms of {predict_trials}")

    model.kneighbors(test)  # warm the retrieval executable
    kn_trials = []
    for _ in range(predict_reps):
        t0 = time.monotonic()
        model.kneighbors(test)
        kn_trials.append(round((time.monotonic() - t0) * 1e3, 3))
    log(f"gate kneighbors: best {min(kn_trials)} ms")

    # Obs stays in whatever state the caller left it: the gate compares
    # gate-to-gate records, so baseline and fresh measure the same
    # (default: uninstrumented) path.
    from knn_tpu.obs.capacity import CapacityTracker

    reqs, conc = 15, 8

    def closed_loop_p50(batcher):
        """One closed-loop c8 trial against ``batcher`` (closed on exit):
        p50 of per-request walls, or None if every request failed. ONE
        load shape for the plain and costed serving trials — the two p50s
        must measure the same thing to be comparable."""
        lats = []
        lock = threading.Lock()
        try:
            batcher.predict(test.features[0], timeout=120)  # warm the path

            def client(cid):
                mine = []
                for i in range(reqs):
                    row = test.features[(cid * reqs + i) % test.num_instances]
                    t0 = time.monotonic()
                    try:
                        batcher.predict(row, timeout=120)
                    except Exception:  # noqa: BLE001 — gate is best-effort
                        continue
                    mine.append((time.monotonic() - t0) * 1e3)
                with lock:
                    lats.extend(mine)

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(conc)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            batcher.close()
        if lats:
            return round(float(np.percentile(lats, 50)), 3)
        return None

    from knn_tpu.models.knn import query_bucket_ladder
    from knn_tpu.serve.artifact import warmup as _serve_warmup

    serve_trials = []
    occ_trials, duty_trials, waste_trials = [], [], []
    with query_bucket_ladder(SERVE_BENCH_BUCKETS):
        # The serving trials dispatch under the bench bucket ladder (the
        # tuned policy the serving docs teach for this single-row
        # closed-loop traffic); occupancy/duty/waste are ARMED gate
        # metrics since PR 10 — the PR 12 baseline refresh holds waste
        # and occupancy at the bucketed values, so a regression back to
        # the 0.955 single-quantum waste fails the gate.
        _serve_warmup(model, batch_sizes=(1,) + SERVE_BENCH_BUCKETS,
                      kinds=("predict",))
        for _ in range(serving_trials):
            capacity = CapacityTracker(64, window_s=120)
            p50 = closed_loop_p50(MicroBatcher(model, max_batch=64,
                                               max_wait_ms=2.0,
                                               capacity=capacity,
                                               buckets=SERVE_BENCH_BUCKETS))
            if p50 is not None:
                serve_trials.append(p50)
            cap_doc = capacity.export()
            occ_trials.append(cap_doc["occupancy_mean"])
            duty_trials.append(cap_doc["duty_cycle"])
            waste_trials.append(cap_doc["padded_row_waste_ratio"])
        log(f"gate serving c8 p50: {serve_trials} ms (occupancy "
            f"{occ_trials}, duty {duty_trials}, padded-row waste "
            f"{waste_trials})")

        # The costed serving p50 (PR 8's c8_cost_p50_ms, gate-shaped):
        # the same closed-loop load with the accounting + capacity layers
        # attached, one p50 per trial — so a cost-attribution overhead
        # regression gates once a baseline entry carries it.
        from knn_tpu.obs.accounting import CostAccountant

        cost_trials = []
        for _ in range(serving_trials):
            p50 = closed_loop_p50(MicroBatcher(
                model, max_batch=64, max_wait_ms=2.0,
                accounting=CostAccountant(),
                capacity=CapacityTracker(64, window_s=120),
                buckets=SERVE_BENCH_BUCKETS))
            if p50 is not None:
                cost_trials.append(p50)
        log(f"gate serving c8 costed p50: {cost_trials} ms")

    d = Path(__file__).parent / "build" / "fixtures"
    ref = Path("/root/reference/datasets")
    train_path = str((ref if ref.exists() else d) / "medium-train.arff")
    try:
        from knn_tpu.native import arff_native

        parse, parser = (lambda: arff_native.parse(train_path)), "native"
    except (ImportError, OSError):
        parse = lambda: pyarff.parse_arff_file(train_path)  # noqa: E731
        parser = "python"
    parse()  # warm the page cache
    ingest_trials = []
    for _ in range(predict_reps):
        t0 = time.monotonic()
        parse()
        ingest_trials.append(round((time.monotonic() - t0) * 1e3, 3))
    log(f"gate ingest[{parser}]: best {min(ingest_trials)} ms")

    # IVF probed retrieval (PR 9): wall + recall trials on the medium
    # preset at a fixed (cells, nprobe) operating point. REPORT-ONLY until
    # a baseline entry carries them (new metrics never gate —
    # obs/regress.py); recall is deterministic for a fixed seed, so its
    # "trial list" is the single measured value.
    from knn_tpu.index.ivf import IVFIndex
    from knn_tpu.obs.quality import recall_at_k

    exact_d, exact_i = model.kneighbors(test)
    ivf = IVFIndex.build(train.features, 64, seed=0)
    # scorer pinned per metric: the armed ivf_kneighbors_wall_ms keeps
    # the HOST scorer its baseline was measured on (auto would silently
    # route this fixture to the device kernel and the two metrics would
    # measure the same thing); the device metric below owns that path.
    ivf.search(train.features, test.features[:8], K, 8,
               scorer="host")  # warm caches
    ivf_trials = []
    for _ in range(predict_reps):
        t0 = time.monotonic()
        ivf_d, ivf_i, _stats = ivf.search(
            train.features, test.features, K, 8, scorer="host")
        ivf_trials.append(round((time.monotonic() - t0) * 1e3, 3))
    ivf_recall = round(float(recall_at_k(
        ivf_i, exact_i, exact_d.astype(np.float64),
        ivf_d.astype(np.float64)).mean()), 4)
    log(f"gate ivf (64 cells, nprobe 8): best {min(ivf_trials)} ms vs "
        f"exact kneighbors {min(kn_trials)} ms, recall {ivf_recall}")
    # PR 13 device scorer: the same probed search forced through the
    # fused gather+score kernel + exact re-rank. Bit-identity to the
    # host trials above is pinned by tests; here only the wall gates.
    ivf.search(train.features, test.features, K, 8,
               scorer="device")  # warm: compile + operand upload
    ivf_dev_trials = []
    for _ in range(predict_reps):
        t0 = time.monotonic()
        dev_d, dev_i, dev_stats = ivf.search(
            train.features, test.features, K, 8, scorer="device")
        ivf_dev_trials.append(round((time.monotonic() - t0) * 1e3, 3))
    if not (np.array_equal(dev_i, ivf_i)
            and np.array_equal(dev_d, ivf_d)):
        raise AssertionError(
            "gate: device ivf scorer diverged from the host scorer")
    log(f"gate ivf device scorer: best {min(ivf_dev_trials)} ms vs host "
        f"{min(ivf_trials)} ms")

    # Roofline-normalized forms of two walls above — the units the full
    # bench reports (Gdist/s for retrieval scan rate, MFU against the
    # f32 peak for predict). Derived 1:1 from their wall trials, so they
    # gate the SAME measurements in hardware-meaningful units: a wall
    # regression that hides behind a data-size change cannot hide here.
    d_feat = int(train.features.shape[1])
    flops = 2 * test.num_instances * train.num_instances * d_feat
    predict_mfu = [
        round(flops / (w / 1e3) / (PEAK_TF_F32 * 1e12), 9)
        for w in predict_trials
    ]
    ivf_dev_gdist = [
        round(dev_stats.candidate_rows * d_feat / (w / 1e3) / 1e9, 6)
        for w in ivf_dev_trials
    ]
    log(f"gate roofline: predict MFU best {max(predict_mfu)}, ivf "
        f"device scan best {max(ivf_dev_gdist)} Gdist/s "
        f"({dev_stats.candidate_rows} candidate rows)")

    import os

    import jax

    dev = jax.devices()[0]
    return {
        "metric": "bench_gate",
        "value": round(min(predict_trials), 3),
        "unit": "ms",
        "vs_baseline": None,
        "batch_buckets": list(SERVE_BENCH_BUCKETS),
        "env": {
            "platform": jax.default_backend(),
            "device_kind": dev.device_kind,
            "cpus": os.cpu_count(),
        },
        "metrics": {
            "predict_wall_ms": {"trials": predict_trials,
                                "direction": "lower", "unit": "ms"},
            "kneighbors_wall_ms": {"trials": kn_trials,
                                   "direction": "lower", "unit": "ms"},
            "serve_c8_p50_ms": {"trials": serve_trials,
                                "direction": "lower", "unit": "ms"},
            "serve_c8_cost_p50_ms": {"trials": cost_trials,
                                     "direction": "lower", "unit": "ms"},
            # PR 8 batching-efficiency telemetry: armed by the PR 10
            # baseline refresh (present in BENCH_GATE_BASELINE.json ->
            # regressions gate; obs/regress.py).
            "serve_c8_occupancy_mean": {"trials": occ_trials,
                                        "direction": "higher",
                                        "unit": "ratio"},
            "serve_c8_duty_cycle": {"trials": duty_trials,
                                    "direction": "lower", "unit": "ratio"},
            "serve_c8_padded_row_waste_ratio": {"trials": waste_trials,
                                                "direction": "lower",
                                                "unit": "ratio"},
            "ingest_ms": {"trials": ingest_trials, "direction": "lower",
                          "unit": "ms", "parser": parser},
            # PR 9 ivf telemetry: report-only until a baseline entry
            # carries them (the PR 8 occupancy/duty rule).
            "ivf_kneighbors_wall_ms": {"trials": ivf_trials,
                                       "direction": "lower", "unit": "ms"},
            "ivf_recall_at_k": {"trials": [ivf_recall],
                                "direction": "higher", "unit": "ratio"},
            # PR 13 device-path telemetry: report-only until a baseline
            # refresh carries it (the same arming rule as above).
            "ivf_device_kneighbors_wall_ms": {"trials": ivf_dev_trials,
                                              "direction": "lower",
                                              "unit": "ms"},
            # PR 16 roofline telemetry: ARMED for env fingerprints whose
            # baseline entry carries them (this box's does); on any
            # other fingerprint there is no baseline entry at all, so
            # they are report-only by construction.
            "predict_mfu": {"trials": predict_mfu,
                            "direction": "higher", "unit": "ratio"},
            "ivf_device_gdist_s": {"trials": ivf_dev_gdist,
                                   "direction": "higher",
                                   "unit": "Gdist/s"},
            # PR 20 arms the exact-scan efficiency column (ROADMAP item
            # 4's cheap first move): the full-bench Gdist/s convention
            # (candidate rows x d / wall) over the SAME kneighbors walls
            # gated above — the exact path scans every train row per
            # test row.
            "device_gdist_s": {
                "trials": [
                    round(test.num_instances * train.num_instances
                          * d_feat / (w / 1e3) / 1e9, 6)
                    for w in kn_trials
                ],
                "direction": "higher", "unit": "Gdist/s"},
        },
    }


_SECONDARY_CONFIGS = {
    "mnist784": bench_mnist,
    "xl": bench_xl,
    "xxl": bench_xxl,
    "ingest": bench_ingest,
    "sharded": bench_sharded,
    "kneighbors": bench_kneighbors,
    "sweepk": bench_sweepk,
    "serving": bench_serving,
    "ivf": bench_ivf,
    "replay": bench_replay,
}

# Per-config whitelist of summary fields beyond the universal ones. The
# FINAL stdout line must stay under the driver's ~2 KB tail capture or the
# round artifact loses its machine-readable record entirely (r4: the
# per-trial lists pushed the single JSON line past the capture window and
# BENCH_r04.json came back with parsed=null and the headline cut off).
# tests/test_bench_summary.py pins the compact line below SUMMARY_BUDGET.
SUMMARY_BUDGET = 1500
_SUMMARY_UNIVERSAL = (
    "metric", "value", "unit", "vs_baseline", "accuracy", "step_ms_median",
)
_SUMMARY_EXTRA = {
    "mnist784": ("tflops", "bf16_qps", "bf16_tflops", "bf16_step_ms_median",
                 "bf16_recall_at_k", "bf16_matmul_tflops", "bf16_matmul_ms"),
    "xl": ("dist_evals_per_sec", "approx_recall_at_k", "approx_wins"),
    "xxl": ("dist_evals_per_sec", "paths_agree"),
    "ingest": ("native_mb_per_s", "native_xl_mb_per_s"),
    "sharded": (),
    "kneighbors": ("auto_ms_per_call", "large_q_qps", "huge_q_qps",
                   "upload_ms", "pipelined_ms_per_call"),
    "sweepk": ("prefix_equivalence",),
    "serving": ("c8_batched_p50_ms", "c8_seq_p50_ms", "c8_batched_qps",
                "batched_beats_seq_c8", "c8_traced_p50_ms",
                "c8_shadow_p50_ms", "shadow_scored", "shadow_shed",
                "shadow_recall", "dropped_requests", "deadline_expired",
                "c8_occupancy_mean", "c8_padded_row_waste_ratio",
                "c8_duty_cycle"),
    "ivf": ("large_speedup_at_recall95", "large_recall", "large_nprobe",
            "large_exact_qps", "medium_speedup_at_recall95",
            "large_device_scorer_speedup", "large_device_gdist_s"),
    "replay": ("replay_p50_ms", "replay_qps", "captured_p50_ms",
               "unpaced_qps", "verified", "divergences", "whatif_p50_ms",
               "whatif_abs_err_ms"),
}


def compact_summary(record):
    """The machine-parseable round summary: the headline record's universal
    fields plus each config reduced to its whitelisted medians. Everything
    else (trial lists, tuning notes) lives in the full record, which is
    printed on an earlier line and written to build/bench_full.json."""
    out = {k: record[k] for k in _SUMMARY_UNIVERSAL if k in record}
    configs = {}
    for name, cfg in record.get("configs", {}).items():
        if "error" in cfg:
            configs[name] = {"error": cfg["error"][:120]}
            continue
        keep = _SUMMARY_UNIVERSAL + _SUMMARY_EXTRA.get(name, ())
        configs[name] = {
            k: cfg[k] for k in keep if k in cfg and cfg[k] is not None
        }
        # The config name implies both; the full record keeps them.
        configs[name].pop("unit", None)
        configs[name].pop("metric", None)
    out["configs"] = configs
    return out


def _span_breakdown(parent):
    from knn_tpu import obs

    return obs.tracer().phase_totals(parent)


def main():
    """Default run: headline + every secondary config. The full record (with
    per-trial lists) goes to stdout first and to build/bench_full.json; the
    FINAL line is the compact summary the driver's tail capture parses.

    The obs tracer runs for the whole session, so every config row carries
    ``span_breakdown`` (its direct instrumented phases) and the record ends
    with the global span aggregate + metric dump — future super-roofline /
    host-stall artifacts arrive self-diagnosing instead of needing the
    hand-forensics of rounds 4-5 (commit de19290)."""
    from knn_tpu import obs

    obs.enable()
    with obs.span("config", config="headline") as hspan:
        record = bench_headline()
    record["span_breakdown"] = _span_breakdown(hspan)
    configs = {}
    for name, fn in _SECONDARY_CONFIGS.items():
        try:
            with obs.span("config", config=name) as cspan:
                configs[name] = fn()
            configs[name]["span_breakdown"] = _span_breakdown(cspan)
        except Exception as e:  # a secondary config must not sink the headline
            log(f"config {name} FAILED: {type(e).__name__}: {e}")
            configs[name] = {"error": f"{type(e).__name__}: {e}"}
    record["configs"] = configs
    record["obs"] = {
        "spans": obs.tracer().aggregate(),
        "metrics": obs.registry().to_json(),
    }
    full = json.dumps(record)
    out = Path(__file__).parent / "build" / "bench_full.json"
    try:
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(full + "\n")
    except OSError as e:
        log(f"could not write {out}: {e}")
    print(full)
    summary = json.dumps(compact_summary(record))
    if len(summary) > SUMMARY_BUDGET:
        log(f"WARNING: summary line {len(summary)} B exceeds the "
            f"{SUMMARY_BUDGET} B budget — trim _SUMMARY_EXTRA")
    print(summary)


if __name__ == "__main__":
    if "--config" in sys.argv:
        fns = dict(_SECONDARY_CONFIGS, headline=bench_headline,
                   mnist=bench_mnist, gate=bench_gate_config)
        idx = sys.argv.index("--config") + 1
        name = sys.argv[idx] if idx < len(sys.argv) else None
        if name not in fns:
            log(f"usage: bench.py [--config {'|'.join(sorted(fns))}]")
            sys.exit(2)
        print(json.dumps(fns[name]()))
    else:
        main()
