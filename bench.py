"""Benchmark: the BASELINE.json headline metric.

Classifies large-test.arff (1,718 queries) against large-train.arff (30,803
rows, 11 features) at k=5 on the available accelerator and reports steady-state
query throughput vs the measured reference baseline (serial C++ at -O0:
138.6 q/s, 12,398 ms — BASELINE.md).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "queries/sec", "vs_baseline": N}
Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

import numpy as np

BASELINE_QPS = 138.6  # reference serial, large k=5 (BASELINE.md)
GOLDEN_ACC = 0.9948
K = 5


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def load_large():
    from knn_tpu.data.arff import load_arff

    ref = Path("/root/reference/datasets")
    if ref.exists():
        return (
            load_arff(str(ref / "large-train.arff")),
            load_arff(str(ref / "large-test.arff")),
            True,
        )
    # Synthetic fallback with the same shapes.
    import subprocess

    out = Path(__file__).parent / "build" / "fixtures"
    if not (out / "large-train.arff").exists():
        subprocess.run(
            [sys.executable, str(Path(__file__).parent / "scripts" / "make_fixtures.py"), str(out)],
            check=True,
        )
    return (
        load_arff(str(out / "large-train.arff")),
        load_arff(str(out / "large-test.arff")),
        False,
    )


def main():
    import jax
    import jax.numpy as jnp

    from knn_tpu.backends.tpu import knn_forward
    from knn_tpu.utils.evaluate import confusion_matrix, accuracy

    t0 = time.monotonic()
    train, test, is_reference = load_large()
    log(f"loaded datasets in {time.monotonic() - t0:.1f}s "
        f"(train {train.features.shape}, test {test.features.shape}, "
        f"reference={is_reference})")
    dev = jax.devices()[0]
    log(f"device: {dev.device_kind} ({dev.platform})")

    train_x = jax.device_put(jnp.asarray(train.features), dev)
    train_y = jax.device_put(jnp.asarray(train.labels), dev)
    test_x = jax.device_put(jnp.asarray(test.features), dev)
    nc = train.num_classes

    def step():
        return knn_forward(train_x, train_y, test_x, k=K, num_classes=nc)

    # Warmup / compile.
    t0 = time.monotonic()
    preds = np.asarray(step())
    log(f"compile+first run: {time.monotonic() - t0:.2f}s")

    acc = accuracy(confusion_matrix(preds, test.labels, test.num_classes))
    log(f"accuracy: {acc:.4f} (golden {GOLDEN_ACC})")
    if is_reference and round(acc, 4) != GOLDEN_ACC:
        log("WARNING: accuracy does not match the reference golden value")

    # Steady state: device-side timing, blocking per iteration.
    times = []
    for _ in range(20):
        t0 = time.monotonic()
        step().block_until_ready()
        times.append(time.monotonic() - t0)
    med = float(np.median(times))
    qps = test.num_instances / med
    log(f"median step: {med * 1e3:.2f} ms over {len(times)} iters "
        f"(min {min(times)*1e3:.2f}, max {max(times)*1e3:.2f})")

    print(
        json.dumps(
            {
                "metric": "large_k5_query_throughput",
                "value": round(qps, 1),
                "unit": "queries/sec",
                "vs_baseline": round(qps / BASELINE_QPS, 1),
                "accuracy": round(acc, 4),
                "median_ms": round(med * 1e3, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
